"""Distributed NS-2D: the full time-stepper over a 2-D device mesh.

Capability parity with /root/reference/assignment-5/ex5-nazifkar (the complete
2-D MPI solver: Cartesian decomposition solver.c:406-520, neighbour-collective
exchange :137-165, staggered shift :167-216, Allreduce reductions :651/:677/
:697, rank-gated special BCs :860-880), built TPU-first on the comm layer.

Equivalence policy — EXACT sequential parity, not the reference's relaxed MPI
parity: the reference's distributed solve accepts a trajectory that differs
from its sequential oracle (rank-local lexicographic sweeps with stale halos,
SURVEY.md §3.2). Here every data dependency of the sequential pipeline is
honoured with a halo refresh before the read, so the distributed run equals
the single-device run bitwise (mod float reduction order) on any mesh:

  step start   exchange(u,v)  — maxElement scans ghosts (solver.c:193 quirk);
                                ghosts must hold current neighbour values
  after BCs    exchange(u,v)  — computeFG's stencil reads BC-written wall
                                strips owned by neighbour shards (the 3-D
                                reference does exactly this, solver.c:635-637)
  before RHS   shift(f,'i'), shift(g,'j') — staggered donor edges (≙ commShift)
  in solve     exchange(p) before each half-sweep (red-black needs fresh
                halos per colour), Neumann walls after both
  after solve  exchange(p)   — adaptUV reads p(i+1,j)/p(i,j+1) across shard
                                edges (≙ the closing commExchange, solver.c:288)

State between chunks is the stacked EXTENDED blocks (ghosts included), so
wall-ghost history (BC values, corner init values) survives host syncs
exactly; normalizePressure weights ghost positions only where they are
physical walls, reproducing the sequential full-array mean (solver.c:204).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops import ns2d as ops
from ..parallel.comm import (
    master_print,
    CartComm,
    get_offsets,
    halo_exchange,
    halo_exchange_bytes,
    halo_shift,
    reduction,
)
from ..parallel.quarters_dist import (
    pack_ext_to_q,
    q_exchange,
    quarters_dispatch,
    unpack_q_to_ext,
)
from ..parallel.stencil2d import (
    ca_halo,
    ca_inner,
    ca_masks,
    ca_rb_iters,
    ca_supported,
    embed_deep,
    rb_exchange_per_sweep,
    rb_split_iter,
    strip_deep,
    wall_flags,
)
from ..utils import dispatch as _dispatch
from ..utils import faultinject as _fi
from ..utils import flags as _flags
from ..utils import telemetry as _tm
from ..utils import xprof as _xprof
from ._driver import clamped_dt
from ..utils.datio import write_pressure, write_velocity
from ..utils.params import Parameter
from ..utils.precision import resolve_dtype
from ..utils.progress import Progress

NOSLIP, SLIP, OUTFLOW, PERIODIC = 1, 2, 3, 4


def _sel(pred, new, old):
    return jnp.where(pred, new, old)


class NS2DDistSolver:
    """Mesh-parallel NS-2D solver; same .par interface as NS2DSolver."""

    CHUNK = 64

    def __init__(self, param: Parameter, comm: CartComm | None = None, dtype=None):
        self._t0_build = time.perf_counter()
        # telemetry is a trace-time decision (utils/flags.py convention):
        # unset leaves every traced program below byte-identical
        metrics = _tm.enabled()
        self._metrics = metrics
        if dtype is None:
            dtype = resolve_dtype(param.tpu_dtype,
                                  record_key="ns2d_dist_dtype")
        if param.tpu_solver == "sor_lex":
            raise ValueError(
                "tpu_solver sor_lex is the single-device ordering oracle "
                "(tools/northstar.py match4096); distributed runs take "
                "sor|mg|fft"
            )
        self.param = param
        self.dtype = dtype
        self.comm = comm if comm is not None else CartComm(
            ndims=2, extents=(param.jmax, param.imax),
            tiers=param.tpu_mesh_tiers,
        )
        self.imax, self.jmax = param.imax, param.jmax
        self.dx = param.xlength / param.imax
        self.dy = param.ylength / param.jmax
        # ragged pad-with-mask decomposition (parallel/ragged2d.py): any
        # grid runs on any mesh, like the reference's sizeOfRank remainder
        # spread (assignment-6/src/comm.c:19-22)
        self.jl, self.il = self.comm.local_shape(
            (self.jmax, self.imax), ragged=True
        )
        Pj, Pi = self.comm.dims
        self.ragged = (self.jl * Pj != self.jmax) or (self.il * Pi != self.imax)
        param = _dispatch.resolve_solver(
            param, obstacles=bool(param.obstacles.strip()),
            ragged=self.ragged,
        )
        self.param = param
        # round 5 (VERDICT r4 item 2): obstacles now COMPOSE with ragged
        # decompositions — the flag field and the ragged live-mask are both
        # global-coordinate-gated constants, so the same per-shard solver
        # runs either (the reference's remainder ranks run the identical
        # solver, assignment-6/src/comm.c:19-22). mg/fft stay divisible-only
        # (coarsening/diagonalization need exact extents).
        if self.ragged and param.tpu_solver in ("mg", "fft"):
            raise ValueError(
                f"tpu_solver {param.tpu_solver} needs a divisible grid/mesh "
                f"(grid {self.jmax}x{self.imax} on {self.comm.dims}); ragged "
                "pad-with-mask runs use tpu_solver sor (obstacles compose)"
            )
        inv_sqr_sum = 1.0 / (self.dx * self.dx) + 1.0 / (self.dy * self.dy)
        self.dt_bound = 0.5 * param.re / inv_sqr_sum
        self.t = 0.0
        self.nt = 0
        # flag-field obstacles: GLOBAL static geometry; every shard slices
        # its mask blocks inside the kernel (ops/obstacle.shard_masks)
        if param.obstacles.strip():
            if param.tpu_solver == "fft":
                raise ValueError(
                    "tpu_solver fft cannot solve obstacle flag fields (the "
                    "stencil is not constant-coefficient); use sor or mg"
                )
            from ..ops import obstacle as obst

            fluid = obst.build_fluid(
                param.imax, param.jmax, self.dx, self.dy, param.obstacles
            )
            self.masks = obst.make_masks(
                fluid, self.dx, self.dy, param.omg, dtype
            )
        else:
            self.masks = None
        self._dt_scale = 1.0  # recovery dt clamp (models/_driver.clamped_dt)
        # fault-injection generation: taken here and in _rebuild_chunk
        # only (see models/ns2d.py for the rationale)
        self._field_faults = _fi.take_field_faults()
        self._build()
        # extended-block state, stacked over the mesh
        self.u, self.v, self.p = self._init_sm()

    # ------------------------------------------------------------------
    def _build(self):
        comm = self.comm
        param = self.param
        dtype = self.dtype
        metrics = self._metrics  # trace-time telemetry gate (see __init__)
        # field-fault injection + recovery dt clamp: both trace-time, both
        # identity when unarmed (the PAMPI_FAULTS-unset jaxpr contract);
        # the generation is taken by __init__/_rebuild_chunk, not here
        field_faults = self._field_faults
        dt_scale = self._dt_scale
        jl, il = self.jl, self.il
        dx, dy = self.dx, self.dy
        Pj = comm.axis_size("j")
        Pi = comm.axis_size("i")
        idx_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32

        def walls():
            return wall_flags(comm)

        # -- boundary conditions, wall-gated (setBoundaryConditions) ----
        def set_bcs_divisible(u, v):
            lo_i, hi_i, lo_j, hi_j = walls()
            bc = param
            if bc.bcLeft == NOSLIP:
                u = u.at[1:-1, 0].set(_sel(lo_i, 0.0, u[1:-1, 0]))
                v = v.at[1:-1, 0].set(_sel(lo_i, -v[1:-1, 1], v[1:-1, 0]))
            elif bc.bcLeft == SLIP:
                u = u.at[1:-1, 0].set(_sel(lo_i, 0.0, u[1:-1, 0]))
                v = v.at[1:-1, 0].set(_sel(lo_i, v[1:-1, 1], v[1:-1, 0]))
            elif bc.bcLeft == OUTFLOW:
                u = u.at[1:-1, 0].set(_sel(lo_i, u[1:-1, 1], u[1:-1, 0]))
                v = v.at[1:-1, 0].set(_sel(lo_i, v[1:-1, 1], v[1:-1, 0]))
            if bc.bcRight == NOSLIP:
                u = u.at[1:-1, -2].set(_sel(hi_i, 0.0, u[1:-1, -2]))
                v = v.at[1:-1, -1].set(_sel(hi_i, -v[1:-1, -2], v[1:-1, -1]))
            elif bc.bcRight == SLIP:
                u = u.at[1:-1, -2].set(_sel(hi_i, 0.0, u[1:-1, -2]))
                v = v.at[1:-1, -1].set(_sel(hi_i, v[1:-1, -2], v[1:-1, -1]))
            elif bc.bcRight == OUTFLOW:
                u = u.at[1:-1, -2].set(_sel(hi_i, u[1:-1, -3], u[1:-1, -2]))
                v = v.at[1:-1, -1].set(_sel(hi_i, v[1:-1, -2], v[1:-1, -1]))
            if bc.bcBottom == NOSLIP:
                v = v.at[0, 1:-1].set(_sel(lo_j, 0.0, v[0, 1:-1]))
                u = u.at[0, 1:-1].set(_sel(lo_j, -u[1, 1:-1], u[0, 1:-1]))
            elif bc.bcBottom == SLIP:
                v = v.at[0, 1:-1].set(_sel(lo_j, 0.0, v[0, 1:-1]))
                u = u.at[0, 1:-1].set(_sel(lo_j, u[1, 1:-1], u[0, 1:-1]))
            elif bc.bcBottom == OUTFLOW:
                u = u.at[0, 1:-1].set(_sel(lo_j, u[1, 1:-1], u[0, 1:-1]))
                v = v.at[0, 1:-1].set(_sel(lo_j, v[1, 1:-1], v[0, 1:-1]))
            if bc.bcTop == NOSLIP:
                v = v.at[-2, 1:-1].set(_sel(hi_j, 0.0, v[-2, 1:-1]))
                u = u.at[-1, 1:-1].set(_sel(hi_j, -u[-2, 1:-1], u[-1, 1:-1]))
            elif bc.bcTop == SLIP:
                v = v.at[-2, 1:-1].set(_sel(hi_j, 0.0, v[-2, 1:-1]))
                u = u.at[-1, 1:-1].set(_sel(hi_j, u[-2, 1:-1], u[-1, 1:-1]))
            elif bc.bcTop == OUTFLOW:
                u = u.at[-1, 1:-1].set(_sel(hi_j, u[-2, 1:-1], u[-1, 1:-1]))
                v = v.at[-2, 1:-1].set(_sel(hi_j, v[-3, 1:-1], v[-2, 1:-1]))
            return u, v

        def set_special_bc_divisible(u):
            lo_i, hi_i, lo_j, hi_j = walls()
            if param.name == "dcavity":
                # lid row, global i in 1..imax-1: skip local col il on the
                # right-wall shard (the reference's loop-bound quirk,
                # solver.c:345-349)
                colmask = jnp.zeros(il + 2, dtype).at[1:-1].set(1.0)
                colmask = colmask.at[-2].mul(1.0 - hi_i.astype(dtype))
                lid = 2.0 - u[-2, :]
                new_row = jnp.where(colmask > 0, lid, u[-1, :])
                u = u.at[-1, :].set(_sel(hi_j, new_row, u[-1, :]))
            elif param.name in ("canal", "canal_obstacle"):
                # parabolic inflow at the left wall, global y coordinate
                joff = get_offsets("j", jl)
                jj = jnp.arange(1, jl + 1, dtype=idx_dtype) + joff
                y = ((jj - 0.5) * dy).astype(dtype)
                prof = y * (param.ylength - y) * 4.0 / (param.ylength**2)
                u = u.at[1:-1, 0].set(_sel(lo_i, prof, u[1:-1, 0]))
            return u

        # -- F/G wall fixups, wall-gated (solver.c:425-435) -------------
        def fg_fixups_divisible(f, g, u, v):
            lo_i, hi_i, lo_j, hi_j = walls()
            f = f.at[1:-1, 0].set(_sel(lo_i, u[1:-1, 0], f[1:-1, 0]))
            f = f.at[1:-1, -2].set(_sel(hi_i, u[1:-1, -2], f[1:-1, -2]))
            g = g.at[0, 1:-1].set(_sel(lo_j, v[0, 1:-1], g[0, 1:-1]))
            g = g.at[-2, 1:-1].set(_sel(hi_j, v[-2, 1:-1], g[-2, 1:-1]))
            return f, g

        # -- ragged pad-with-mask wall handling (parallel/ragged2d.py):
        # same arithmetic as the divisible forms, selected by GLOBAL index
        # so hi walls may sit anywhere inside (or before) a trailing shard
        if self.ragged:
            from ..parallel import ragged2d as rg

            def set_bcs(u, v):
                return rg.set_bcs_ragged(
                    u, v, param, comm, jl, il, self.jmax, self.imax
                )

            def set_special_bc(u):
                return rg.set_special_bc_ragged(
                    u, param, comm, jl, il, self.jmax, self.imax, dy,
                    idx_dtype,
                )

            def fg_fixups(f, g, u, v):
                return rg.fg_fixups_ragged(
                    f, g, u, v, comm, jl, il, self.jmax, self.imax
                )
        else:
            set_bcs = set_bcs_divisible
            set_special_bc = set_special_bc_divisible
            fg_fixups = fg_fixups_divisible

        # -- pressure solve (RB SOR; ≙ solve, solver.c:586-660) ---------
        dx2, dy2 = dx * dx, dy * dy
        idx2, idy2 = 1.0 / dx2, 1.0 / dy2
        factor = param.omg * 0.5 * (dx2 * dy2) / (dx2 + dy2)
        epssq = param.eps * param.eps
        norm = float(self.imax * self.jmax)

        def _solve_sor(p, rhs, cap=None):
            """Communication-avoiding red-black solve (stencil2d.ca_*): one
            depth-2n halo exchange per n exact local iterations (n =
            tpu_ca_inner clamped by shard extents; trajectory identical to
            the exchange-per-half-sweep form). Extent-1 shards use the
            classic per-half-sweep fallback. `cap` (the residual-adaptive
            budget, tpu_itermax_adaptive) dynamically tightens the static
            itermax; None traces the historical loop."""
            limit = param.itermax if cap is None else cap
            supported = ca_supported(jl, il)
            n = ca_inner(param, jl, il) if supported else 1
            H = ca_halo(n, ragged=self.ragged) if supported else 1
            masks = ca_masks(jl, il, H, self.jmax, self.imax, dtype)
            pd = embed_deep(p, H)
            rd = halo_exchange(embed_deep(rhs, H), comm, depth=H)

            def cond(c):
                _, res, it = c
                return jnp.logical_and(res >= epssq, it < limit)

            def body(c):
                pd, _, it = c
                if supported:
                    pd = halo_exchange(pd, comm, depth=H)
                    pd, r2 = ca_rb_iters(pd, rd, n, masks, factor, idx2, idy2)
                else:
                    pd, r2 = rb_exchange_per_sweep(
                        pd, rd, masks, comm, factor, idx2, idy2,
                        ragged=self.ragged,
                    )
                res = reduction(r2, comm, "sum") / norm
                if _flags.debug():
                    master_print(comm, "{} Residuum: {}", it + (n - 1), res)
                return pd, res, it + n

            pd, res, it = lax.while_loop(
                cond, body,
                (pd, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32)),
            )
            return halo_exchange(strip_deep(pd, H), comm), res, it

        def _solve_sor_split(p, rhs, cap=None):
            """The sweep-split twin of _solve_sor (dispatched with the
            overlapped schedule, ROADMAP item 3): same n-iteration
            residual cadence as the CA form — the trajectory is bitwise
            identical (the CA discipline already equals the per-half-
            sweep form) — but each half-sweep posts its depth-1 exchange
            behind the interior update (stencil2d.rb_split_iter), so on
            a solve-dominated step no exchange sits serialized on the
            critical path. Runs on the plain halo-1 layout; the rim-2
            interior mask gates the merge."""
            from ..parallel import overlap as _ovl
            from ..parallel.comm import persistent_exchange

            limit = param.itermax if cap is None else cap
            supported = ca_supported(jl, il)
            n = ca_inner(param, jl, il) if supported else 1
            masks = ca_masks(jl, il, 1, self.jmax, self.imax, dtype)
            int_mask = _ovl.interior_mask(
                (jl, il), 2, partitioned=(Pj > 1, Pi > 1))
            sched1 = persistent_exchange(comm, 1, dtype)

            def cond(c):
                _, res, it = c
                return jnp.logical_and(res >= epssq, it < limit)

            def body(c):
                p, _, it = c
                r2 = None
                for _k in range(n):
                    p, r2 = rb_split_iter(
                        p, rhs, masks, sched1, int_mask, factor, idx2,
                        idy2, ragged=self.ragged)
                res = reduction(r2, comm, "sum") / norm
                if _flags.debug():
                    master_print(comm, "{} Residuum: {}", it + (n - 1), res)
                return p, res, it + n

            p, res, it = lax.while_loop(
                cond, body,
                (p, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32)),
            )
            return halo_exchange(p, comm), res, it

        # -- quarter-layout production pressure solve (the round-3 wiring of
        # the headline Pallas kernel into the distributed path; same dispatch
        # contract as models/poisson_dist) --------------------------------
        plain_sor = param.tpu_solver not in ("mg", "fft") and self.masks is None
        rb_q, qg, n_q, pallas_q = quarters_dispatch(
            param, self.jmax, self.imax, jl, il, dx, dy, dtype,
            "ns2d_dist", plain_sor=plain_sor and not self.ragged,
        )
        # ragged Pallas fast path (round 5, VERDICT r4 item 2): the
        # compressed quarters layout cannot carry ragged walls, but the
        # flag-masked per-shard kernel can — the live region IS a flag
        # field (all-fluid masks; the kernel's global-coordinate gating
        # already excludes dead cells, ops/sor_obsdist). Dispatched only
        # when the kernel actually is (off-TPU the jnp case keeps
        # _solve_sor's bitwise CA discipline).
        # `tpu_sor_layout checkerboard` forces the masked kernel in dist
        # context (interpret off-TPU — the dryrun/test mode; the obsdist
        # kernel IS the distributed masked-checkerboard layout)
        force_masked = param.tpu_sor_layout == "checkerboard"
        solve_ragged_k = None
        if self.ragged and plain_sor:
            from ..models.poisson import _use_pallas
            from ..ops import obstacle as obst

            if force_masked or _use_pallas("auto", dtype):
                # the dispatch predicate gates the BUILD too: the all-fluid
                # masks are host-side global-sized arrays — off-TPU
                # unforced runs keep _solve_sor without paying for them
                m_live = obst.make_masks(
                    np.ones((self.jmax + 2, self.imax + 2), bool),
                    dx, dy, param.omg, dtype,
                )
                cand, used_k = obst.make_dist_obstacle_solver(
                    comm, self.imax, self.jmax, jl, il, dx, dy, param.eps,
                    param.itermax, m_live, dtype, ca_n=param.tpu_ca_inner,
                    sor_inner=param.tpu_sor_inner, ragged=True,
                    record_key="ns2d_dist",
                    backend="pallas" if force_masked else "auto",
                )
                if used_k:
                    solve_ragged_k = cand
                    pallas_q = True
        if rb_q is None and solve_ragged_k is None:
            tag = (
                "jnp_ca" if plain_sor else f"other_{param.tpu_solver}"
                if self.masks is None else "obstacle (see obstacle_dist)"
            )
            if self.ragged:
                tag += " ragged"
            _dispatch.record("ns2d_dist", tag)

        def _solve_sor_quarters(p, rhs, cap=None):
            """Stacked-quarter CA solve on the halo-1 extended blocks the
            time-stepper carries; returns the exchanged halo-1 block like
            _solve_sor (adaptUV reads p across shard edges)."""
            limit = param.itermax if cap is None else cap
            joff = get_offsets("j", jl)
            ioff = get_offsets("i", il)
            qoffs = jnp.stack(
                [(joff // 2).astype(jnp.int32), (ioff // 2).astype(jnp.int32)]
            )
            rq = q_exchange(pack_ext_to_q(rhs, qg), comm, qg)
            xq = pack_ext_to_q(p, qg)

            def cond(c):
                _, res, it = c
                return jnp.logical_and(res >= epssq, it < limit)

            def body(c):
                xq, _, it = c
                xq = q_exchange(xq, comm, qg)
                xq, r2 = rb_q(qoffs, xq, rq)
                res = reduction(r2, comm, "sum") / norm
                if _flags.debug():
                    master_print(comm, "{} Residuum: {}", it + (n_q - 1), res)
                return xq, res, it + n_q

            xq, res, it = lax.while_loop(
                cond, body,
                (xq, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32)),
            )
            return halo_exchange(unpack_q_to_ext(xq, qg), comm), res, it

        # pre-resolution of the overlap knob for the solve builders (the
        # recorded decision happens after the fused build below — this
        # predicate only selects the sweep-split smoother forms, whose
        # values are bitwise the serial forms either way). It mirrors
        # resolve_overlap's statically-known ineligibility (off / field
        # faults / fused knob off); the one input not known yet — the
        # fused probe failing at build — is healed by the serial MG
        # rebuild next to the sweep_split record below.
        ovl_pre = (param.tpu_overlap != "off"
                   and not field_faults
                   and param.tpu_fuse_phases != "off"
                   and (param.tpu_overlap == "on"
                        or jax.default_backend() == "tpu"))
        mg_serial_rebuild = None
        if param.tpu_solver == "fft":
            from ..ops.dctpoisson import make_dist_dct_solve_2d

            solve = make_dist_dct_solve_2d(
                comm, self.imax, self.jmax, jl, il, dx, dy, dtype
            )
        elif param.tpu_solver == "mg":
            if self.masks is not None:
                # the only floor-reaching solver on obstacle-at-scale
                # configs, now also on a mesh (VERDICT r3 item 6)
                from ..ops.multigrid import make_dist_obstacle_mg_solve_2d

                solve, mg_pallas = make_dist_obstacle_mg_solve_2d(
                    comm, self.imax, self.jmax, jl, il, dx, dy,
                    param.eps, param.itermax, self.masks, dtype,
                    stall_rtol=param.tpu_mg_stall_rtol,
                    fused=param.tpu_mg_fused,
                )
                # the MG factory reports per-shard Pallas smoothing the
                # same way the obstacle SOR solver does: relax check_vma
                pallas_q = pallas_q or mg_pallas
            else:
                from ..ops.multigrid import make_dist_mg_solve_2d

                solve, mg_pallas = make_dist_mg_solve_2d(
                    comm, self.imax, self.jmax, jl, il, dx, dy,
                    param.eps, param.itermax, dtype,
                    stall_rtol=param.tpu_mg_stall_rtol, split=ovl_pre,
                    fused=param.tpu_mg_fused,
                )
                pallas_q = pallas_q or mg_pallas
                if ovl_pre:
                    def mg_serial_rebuild():
                        s2, _ = make_dist_mg_solve_2d(
                            comm, self.imax, self.jmax, jl, il, dx, dy,
                            param.eps, param.itermax, dtype,
                            stall_rtol=param.tpu_mg_stall_rtol,
                            split=False, fused=param.tpu_mg_fused,
                        )
                        return s2
        elif self.masks is not None:
            from ..ops.obstacle import make_dist_obstacle_solver

            solve, obs_pallas = make_dist_obstacle_solver(
                comm, self.imax, self.jmax, jl, il, dx, dy,
                param.eps, param.itermax, self.masks, dtype,
                ca_n=param.tpu_ca_inner, sor_inner=param.tpu_sor_inner,
                ragged=self.ragged,
                backend="pallas" if force_masked else "auto",
            )
            # the obstacle solver reports whether it dispatched its
            # per-shard Pallas kernel: relax check_vma then
            pallas_q = pallas_q or obs_pallas
        elif rb_q is not None:
            solve = _solve_sor_quarters
        elif solve_ragged_k is not None:
            solve = solve_ragged_k
        else:
            solve = _solve_sor

        # -- fused step-phase kernels (ops/ns2d_fused.py): the per-shard
        # non-solve phases (BCs + special BC + FG + fixups + RHS, then
        # adaptUV) collapse into two global-coordinate-gated Pallas kernels
        # around the solve — PRE on the depth-H deep-halo block (one
        # exchange buys the whole validity chain, the CA discipline), POST
        # on the plain extended block (adaptUV reads only center/+1).
        # dt stays the jnp reduction (the deep-exchanged block contains the
        # same global value set, so the ghost-inclusive max is unchanged).
        # Ragged shards are the same kernels at uneven block bounds (global
        # gating + the POST live-mask multiply); obstacle runs feed the
        # per-shard global-constant flag slices at call time (fluid=True).
        from ..ops.ns2d_fused import FUSE_DEEP_HALO, probe_fused_2d

        fuse_why_not = None
        if min(jl, il) < FUSE_DEEP_HALO:
            fuse_why_not = f"shard extents < deep halo {FUSE_DEEP_HALO}"
        fused_k = None
        if _dispatch.resolve_fuse_phases(
            param, "auto", dtype, probe_fused_2d, "ns2d_dist_phases",
            why_not=fuse_why_not,
        ):
            from ..ops import ns2d_fused as nf

            try:
                pre_k, pad_deep, unpad_deep, _hk = nf.make_fused_pre_2d(
                    param, self.jmax, self.imax, dx, dy, dtype,
                    jl=jl, il=il, ext_pad=FUSE_DEEP_HALO - 1,
                    fluid=True if self.masks is not None else None,
                    prof_dtype=idx_dtype,
                )
                post_k, pad_ext, unpad_ext, _hk2 = nf.make_fused_post_2d(
                    param, self.jmax, self.imax, dx, dy, dtype,
                    jl=jl, il=il,
                    fluid=True if self.masks is not None else None,
                    ragged=self.ragged,
                )
                fused_k = (pre_k, post_k)
                pallas_q = True
            except ValueError as exc:  # VMEM-infeasible shard geometry
                _dispatch.record("ns2d_dist_phases", f"jnp ({exc})")

        # -- comm/compute overlap (ROADMAP item 2): the double-buffered
        # interior/boundary schedule rides the fused deep-halo step only;
        # the serial schedule stays the parity oracle (`off` is bitwise
        # the historical program — the CONTRACTS.json hash contract)
        ovl_why = None
        if fused_k is None:
            ovl_why = "needs the fused deep-halo step (tpu_fuse_phases)"
        elif field_faults:
            ovl_why = ("PAMPI_FAULTS field faults armed (in-step writes "
                       "would postdate the posted exchange)")
        overlap = _dispatch.resolve_overlap(
            param, "overlap_ns2d_dist", why_not=ovl_why)
        self._overlap = overlap
        self._overlap_plan = None  # set by the overlap block when the
        #   grid-restricted halves dispatch (tpu_overlap_restrict)
        # sweep split (ROADMAP item 3 layer 2): with the overlapped
        # schedule dispatched, the jnp RB-SOR convergence loop swaps to
        # the per-half-sweep split form — bitwise the CA trajectory,
        # with every depth-1 exchange posted behind an interior update.
        # Pallas solve paths keep their serial sweeps (the kernel reads
        # its whole block; a split needs kernel surgery, not a loop
        # swap) and record why.
        if overlap and solve is _solve_sor:
            solve = _solve_sor_split
            _dispatch.record("sweep_split_ns2d_dist", "split (jnp rb-sor)")
        elif overlap and param.tpu_solver == "mg" and self.masks is None:
            _dispatch.record("sweep_split_ns2d_dist",
                             "split (mg jnp-smoother levels)")
        elif overlap:
            _dispatch.record("sweep_split_ns2d_dist",
                             "serial (pallas/other solve)")
        elif mg_serial_rebuild is not None:
            # the pre-resolution guessed overlap but the fused probe
            # failed at build: drop the split smoother so the traced
            # program matches the recorded serial schedule
            solve = mg_serial_rebuild()

        # residual-adaptive itermax (tpu_itermax_adaptive, ROADMAP item
        # 1's last open bullet): the previous step's (res, it) shrinks
        # the NEXT solve's sweep budget inside the chunk loop — the cap
        # rides the chunk carry only (external arity unchanged, resets
        # to the full itermax at every chunk dispatch). Dist SOR paths
        # only: mg counts cycles, fft does not iterate, the obstacle
        # solvers carry their own loops.
        adapt_n = int(param.tpu_itermax_adaptive)
        use_cap = adapt_n > 0 and solve in (
            _solve_sor, _solve_sor_split, _solve_sor_quarters)
        if adapt_n > 0:
            _dispatch.record(
                "itermax_adaptive_ns2d_dist",
                f"adaptive (+{adapt_n} slack)" if use_cap
                else "static (solve path carries no sweep budget)")
        itermax_i = jnp.asarray(param.itermax, jnp.int32)

        def next_cap(res, it):
            # converged within the budget -> cap the next solve at
            # it + slack; a capped/non-converged solve restores the full
            # itermax so the budget never wedges a hard step
            return jnp.where(res < epssq,
                             jnp.minimum(itermax_i, it + adapt_n),
                             itermax_i)

        # -- weighted mean for normalizePressure ------------------------
        def wall_weight():
            if self.ragged:
                from ..parallel import ragged2d as rg

                return rg.wall_weight_ragged(
                    comm, jl, il, self.jmax, self.imax, dtype
                )
            lo_i, hi_i, lo_j, hi_j = walls()
            one = jnp.ones((), dtype)
            rowv = jnp.ones(jl + 2, dtype)
            rowv = rowv.at[0].set(_sel(lo_j, one, 0.0 * one))
            rowv = rowv.at[-1].set(_sel(hi_j, one, 0.0 * one))
            colv = jnp.ones(il + 2, dtype)
            colv = colv.at[0].set(_sel(lo_i, one, 0.0 * one))
            colv = colv.at[-1].set(_sel(hi_i, one, 0.0 * one))
            return rowv[:, None] * colv[None, :]

        nfull = float((self.imax + 2) * (self.jmax + 2))
        gmasks = self.masks
        if gmasks is not None:
            from ..ops.obstacle import (
                adapt_uv_obstacle,
                apply_obstacle_velocity_bc,
                mask_fg,
                shard_masks,
            )

            # ragged ceil-division overhang (0 when divisible): the HI-side
            # zero-pad that keeps trailing-shard mask slices from clamping
            # (dead cells read zero masks)
            from ..parallel.stencil2d import ceil_overhang

            over_j = ceil_overhang(Pj, jl, self.jmax)
            over_i = ceil_overhang(Pi, il, self.imax)

            def local_masks():
                # must run INSIDE the shard_map trace (mesh offsets)
                return shard_masks(gmasks, jl, il, over_j, over_i)

            def fused_flag_blocks():
                """Per-shard deep-halo and extended slices of the global 0/1
                fluid flag for the fused kernels (the shard_masks
                global-constant-slice convention: overlapping slices agree
                across shards), in the kernels' padded layouts. Beyond-global
                deep-halo cells read flag 0 — their outputs are stripped or
                interior-gated. Loop-invariant constant gathers: XLA hoists
                them out of the chunk's while loop."""
                H = FUSE_DEEP_HALO
                joff = get_offsets("j", jl)
                ioff = get_offsets("i", il)
                fl = gmasks.fluid
                wide = jnp.pad(
                    fl, ((H - 1, over_j + H - 1), (H - 1, over_i + H - 1))
                )
                deep = lax.dynamic_slice(
                    wide, (joff, ioff), (jl + 2 * H, il + 2 * H)
                )
                hi = jnp.pad(fl, ((0, over_j), (0, over_i)))
                ext = lax.dynamic_slice(hi, (joff, ioff), (jl + 2, il + 2))
                return pad_deep(deep), pad_ext(ext)

        def normalize_pressure(p):
            if gmasks is not None:
                # fluid-weighted mean (obstacle cells excluded), ghost ring
                # counted once via the wall gate — ≙ normalize_pressure_fluid
                w = wall_weight() * local_masks().fluid
                total = reduction(jnp.sum(p * w), comm, "sum")
                count = reduction(jnp.sum(w), comm, "sum")
                return p - total / count
            s = reduction(jnp.sum(p * wall_weight()), comm, "sum")
            return p - s / nfull

        # -- CFL timestep (maxElement incl. ghosts + Allreduce MAX) ------
        def cfl_from_maxima(umax, vmax):
            # the scalar tail, shared with the overlapped step (whose
            # maxima ride the carry from the previous POST kernel)
            inf = jnp.asarray(jnp.inf, dtype)
            dt = jnp.minimum(
                jnp.asarray(self.dt_bound, dtype),
                jnp.minimum(
                    jnp.where(umax > 0, dx / umax, inf),
                    jnp.where(vmax > 0, dy / vmax, inf),
                ),
            )
            return dt * param.tau

        def compute_dt(u, v):
            umax = reduction(jnp.max(jnp.abs(u)), comm, "max")
            vmax = reduction(jnp.max(jnp.abs(v)), comm, "max")
            return cfl_from_maxima(umax, vmax)

        adaptive = param.tau > 0.0

        # -- one full timestep ------------------------------------------
        def step_phases(u, v, p, nt, cap=None):
            """All phases of one timestep up to (and incl.) the pressure
            solve; step() appends the projection, debug_kernel returns the
            intermediates (the automated heir of the reference's test.c
            halo dump, SURVEY.md §4.1). `cap` is the residual-adaptive
            sweep budget (None = the historical static-itermax trace)."""
            u, v, p = _fi.apply_field_faults(field_faults, nt, u=u, v=v, p=p)
            u = halo_exchange(u, comm)
            v = halo_exchange(v, comm)
            dt = compute_dt(u, v) if adaptive else jnp.asarray(param.dt, dtype)
            dt = clamped_dt(dt, dt_scale)
            u, v = set_bcs(u, v)
            u = set_special_bc(u)
            u = halo_exchange(u, comm)
            v = halo_exchange(v, comm)
            if gmasks is not None:
                # needs the fully-exchanged post-BC state (the single-device
                # op reads the whole array at once); its own halo-cell
                # outputs are refreshed by one more exchange
                u, v = apply_obstacle_velocity_bc(u, v, local_masks())
                u = halo_exchange(u, comm)
                v = halo_exchange(v, comm)
            f, g = ops.compute_fg_interior(
                u, v, dt, param.re, param.gx, param.gy, param.gamma, dx, dy
            )
            f, g = fg_fixups(f, g, u, v)
            if gmasks is not None:
                f, g = mask_fg(f, g, u, v, local_masks())
            f = halo_shift(f, comm, "i")
            g = halo_shift(g, comm, "j")
            rhs = ops.compute_rhs(f, g, dt, dx, dy)
            p = lax.cond(nt % 100 == 0, normalize_pressure, lambda q: q, p)
            p, res, it = (solve(p, rhs, cap) if cap is not None
                          else solve(p, rhs))
            return u, v, f, g, rhs, p, dt, res, it

        def step(u, v, p, t, nt, cap=None):
            u, v, f, g, _rhs, p, dt, res, it = step_phases(u, v, p, nt,
                                                           cap)

            def adapt(u, v):
                if gmasks is not None:
                    return adapt_uv_obstacle(
                        u, v, f, g, p, dt, dx, dy, local_masks()
                    )
                return ops.adapt_uv(u, v, f, g, p, dt, dx, dy)

            if not self.ragged:
                u, v = adapt(u, v)
            else:
                # ragged projection: update ONLY the true global interior.
                # The single-device adapt never touches ghost rows, but here
                # the global ghost ring can be interior-stored — clobbering
                # it would change what next step's ghost-inclusive CFL scan
                # (maxElement quirk) sees; dead cells are zeroed so halo
                # garbage cannot reach that scan either. One gating block
                # for the plain AND obstacle projections — the discipline
                # cannot drift between them.
                from ..parallel import ragged2d as rg

                gj, gi = rg.global_index_vectors(comm, jl, il)
                interior = (
                    (gj >= 1) & (gj <= self.jmax)
                    & (gi >= 1) & (gi <= self.imax)
                )
                live = rg.live_masks(comm, jl, il, self.jmax, self.imax, dtype)
                ua, va = adapt(u, v)
                u = jnp.where(interior, ua, u) * live
                v = jnp.where(interior, va, v) * live
            # t accumulates in high precision regardless of the field dtype
            # (bfloat16 would stall t once ulp/2 > dt and never reach te)
            t_next = t + dt.astype(idx_dtype)
            if _flags.verbose():
                # printed AFTER t += dt, matching A5 main.c:52-57
                master_print(comm, "TIME {} , TIMESTEP {}", t_next, dt)
            capt = (next_cap(res, it),) if cap is not None else ()
            if metrics:
                # mesh-global |u|/|v| maxima (replicated, like res) — the
                # in-band telemetry scalars; Allreduce MAX only on this path
                um = reduction(jnp.max(jnp.abs(u)), comm, "max")
                vm = reduction(jnp.max(jnp.abs(v)), comm, "max")
                return (u, v, p, t_next, nt + 1, res, it, dt, um, vm) + capt
            return (u, v, p, t_next, nt + 1) + capt

        def step_fused(u, v, p, t, nt, cap=None, strips=None):
            """The fused-phase twin of step(): one deep exchange feeds the
            PRE kernel (BCs+FG+RHS per shard, redundant halo recompute
            bitwise-consistent across shards), the solve is unchanged, the
            POST kernel projects on the exchanged extended blocks.
            `strips` is the depth-scheduled variant (tpu_exchange_depth):
            the slow-tier axis's ghost strips come from the K-block's
            captured exchange (parallel/comm.paste_axis_strips) instead
            of a fresh per-step collective — relaxed parity, staleness
            bounded by the depth block."""
            pre_k, post_k = fused_k
            H = FUSE_DEEP_HALO
            u, v, p = _fi.apply_field_faults(field_faults, nt, u=u, v=v, p=p)
            if strips is None:
                ud = halo_exchange(embed_deep(u, H), comm, depth=H)
                vd = halo_exchange(embed_deep(v, H), comm, depth=H)
            else:
                from ..parallel.comm import paste_axis_strips

                (lo_u, hi_u), (lo_v, hi_v) = strips
                ud = paste_axis_strips(
                    embed_deep(u, H), comm, dax, H, lo_u, hi_u)
                vd = paste_axis_strips(
                    embed_deep(v, H), comm, dax, H, lo_v, hi_v)
            # ghost-inclusive CFL max: the deep block carries the same
            # global value set (owned + fresh neighbour copies + wall
            # ghosts + dead zeros), so the max reduction is unchanged
            dt = compute_dt(ud, vd) if adaptive else jnp.asarray(param.dt, dtype)
            dt = clamped_dt(dt, dt_scale)
            joff = get_offsets("j", jl)
            ioff = get_offsets("i", il)
            offs = jnp.stack([joff, ioff]).astype(jnp.int32)
            dt11 = jnp.full((1, 1), dt, dtype)
            pre_extra = post_extra = ()
            if gmasks is not None:
                flg_deep, flg_ext = fused_flag_blocks()
                pre_extra = (flg_deep,)
                post_extra = (flg_ext,)
            upd, vpd, fpd, gpd, rpd = pre_k(
                offs, dt11, pad_deep(ud), pad_deep(vd), *pre_extra
            )
            u = strip_deep(unpad_deep(upd), H)
            v = strip_deep(unpad_deep(vpd), H)
            f = strip_deep(unpad_deep(fpd), H)
            g = strip_deep(unpad_deep(gpd), H)
            rhs = strip_deep(unpad_deep(rpd), H)
            p = lax.cond(nt % 100 == 0, normalize_pressure, lambda q: q, p)
            p, _res, _it = (solve(p, rhs, cap) if cap is not None
                            else solve(p, rhs))
            up, vp, um_l, vm_l = post_k(
                offs, dt11, pad_ext(u), pad_ext(v), pad_ext(f), pad_ext(g),
                pad_ext(p), *post_extra,
            )
            u = unpad_ext(up)
            v = unpad_ext(vp)
            t_next = t + dt.astype(idx_dtype)
            if _flags.verbose():
                master_print(comm, "TIME {} , TIMESTEP {}", t_next, dt)
            capt = (next_cap(_res, _it),) if cap is not None else ()
            if metrics:
                # the POST kernel's carried maxima are per-shard: one
                # Allreduce MAX makes them the global telemetry scalars
                um = reduction(um_l, comm, "max")
                vm = reduction(vm_l, comm, "max")
                return (u, v, p, t_next, nt + 1, _res, _it, dt,
                        um, vm) + capt
            return (u, v, p, t_next, nt + 1) + capt

        if overlap:
            # -- overlapped fused step (parallel/overlap.py): the deep
            # exchange for step N+1 is posted right after step N's POST
            # and carried as a double-buffered (ud, vd) pair + the CFL
            # maxima + a generation tag; PRE runs twice — interior half
            # on the stale re-embedded block (no dependency on the
            # exchange anywhere in its cone), boundary half on the
            # buffered exchanged block — merged by the interior mask.
            # Trajectory == step_fused's bitwise (the interior cone
            # avoids the strips; max is reduction-order exact).
            from ..ops import ns2d_fused as nf
            from ..ops.ns2d_fused import OVERLAP_RIM
            from ..parallel import overlap as _ovl
            from ..parallel.comm import persistent_exchange

            H = FUSE_DEEP_HALO
            deep_sched = persistent_exchange(comm, H, dtype)
            # axis-aware rim: a size-1 mesh axis exchanges nothing, so
            # its sides are bit-identical between the stale block and
            # the double buffer — the rim (and the boundary half's
            # sweep) drops there (parallel/overlap.interior_slices)
            part = (Pj > 1, Pi > 1)
            int_mask = _ovl.interior_mask((jl, il), OVERLAP_RIM,
                                          partitioned=part)
            # grid restriction (tpu_overlap_restrict): band the two PRE
            # halves over the leading axis — interior core rows for the
            # interior half, OVERLAP_RIM bands (plus every row when the
            # column axis is partitioned) for the boundary half
            br_, _hh, wp_, nb_ = nf.fused_deep_layout_2d(
                jl, il, dtype, H - 1)
            plan = _ovl.region_plan((jl, il), OVERLAP_RIM, H - 1,
                                    br_, nb_, wp_, part)
            restrict = _dispatch.resolve_overlap_restrict(
                param, "overlap_grid_ns2d_dist", plan)
            self._overlap_plan = plan if restrict else None
            pre_int = pre_bnd = None
            if restrict:
                fl_arg = True if self.masks is not None else None
                pre_int = nf.make_fused_pre_2d(
                    param, self.jmax, self.imax, dx, dy, dtype,
                    jl=jl, il=il, ext_pad=H - 1, fluid=fl_arg,
                    prof_dtype=idx_dtype,
                    grid_bands=plan["int_bands"])[0]
                pre_bnd = nf.make_fused_pre_2d(
                    param, self.jmax, self.imax, dx, dy, dtype,
                    jl=jl, il=il, ext_pad=H - 1, fluid=fl_arg,
                    prof_dtype=idx_dtype,
                    grid_bands=plan["bnd_bands"])[0]

            def exchange_buffers(u, v):
                """Post the next step's deep exchange (the double
                buffer's fill half)."""
                return (deep_sched(embed_deep(u, H)),
                        deep_sched(embed_deep(v, H)))

            def buffer_maxima(ud, vd):
                """Ghost-inclusive CFL maxima of the freshly exchanged
                deep blocks — the serial step's compute_dt inputs, used
                only for the chunk-prologue generation (steps >= 2 carry
                the POST kernel's maxima instead)."""
                return (reduction(jnp.max(jnp.abs(ud)), comm, "max"),
                        reduction(jnp.max(jnp.abs(vd)), comm, "max"))

            def step_overlap(u, v, p, t, nt, ud, vd, um, vm, gen,
                             cap=None):
                pre_k, post_k = fused_k
                # the restricted halves (when dispatched) are the SAME
                # kernel on banded grids; values inside each band are
                # bitwise the full sweep's (globally gated writes), and
                # the merge mask selects only band-covered cells
                pre_i = pre_int if pre_int is not None else pre_k
                pre_b = pre_bnd if pre_bnd is not None else pre_k
                dt = (cfl_from_maxima(um, vm) if adaptive
                      else jnp.asarray(param.dt, dtype))
                # stale-buffer detector: a generation-skewed double
                # buffer poisons dt (NaN t -> drive-loop divergence)
                dt = _ovl.generation_guard(dt, gen, nt)
                dt = clamped_dt(dt, dt_scale)
                joff = get_offsets("j", jl)
                ioff = get_offsets("i", il)
                offs = jnp.stack([joff, ioff]).astype(jnp.int32)
                dt11 = jnp.full((1, 1), dt, dtype)
                pre_extra = post_extra = ()
                if gmasks is not None:
                    flg_deep, flg_ext = fused_flag_blocks()
                    pre_extra = (flg_deep,)
                    post_extra = (flg_ext,)
                ints = pre_i(offs, dt11, pad_deep(embed_deep(u, H)),
                             pad_deep(embed_deep(v, H)), *pre_extra)
                bnds = pre_b(offs, dt11, pad_deep(ud), pad_deep(vd),
                             *pre_extra)
                u, v, f, g, rhs = _ovl.merge_halves(
                    int_mask,
                    [strip_deep(unpad_deep(a), H) for a in ints],
                    [strip_deep(unpad_deep(b), H) for b in bnds])
                p = lax.cond(nt % 100 == 0, normalize_pressure,
                             lambda q: q, p)
                p, _res, _it = (solve(p, rhs, cap) if cap is not None
                                else solve(p, rhs))
                up, vp, um_l, vm_l = post_k(
                    offs, dt11, pad_ext(u), pad_ext(v), pad_ext(f),
                    pad_ext(g), pad_ext(p), *post_extra,
                )
                u = unpad_ext(up)
                v = unpad_ext(vp)
                # next step's CFL maxima: POST's carried per-shard maxima
                # over the valid extended cells — the same global value
                # set the serial step's exchanged-block scan sees
                um = reduction(um_l, comm, "max")
                vm = reduction(vm_l, comm, "max")
                # post step N+1's exchange NOW: its results feed only the
                # carried buffers (the boundary half, one iteration
                # later) — nothing else in the trace depends on them
                ud, vd = exchange_buffers(u, v)
                t_next = t + dt.astype(idx_dtype)
                if _flags.verbose():
                    master_print(comm, "TIME {} , TIMESTEP {}", t_next, dt)
                capt = (next_cap(_res, _it),) if cap is not None else ()
                return (u, v, p, t_next, nt + 1, ud, vd, um, vm, nt + 1,
                        _res, _it, dt) + capt

        step_impl = step if fused_k is None else step_fused
        te = param.te
        chunk = self.CHUNK
        # K-step fused chunks (ISSUE 17): K=1 keeps the historical
        # while-body verbatim (jaxpr-hash identity); K>=2 advances the
        # loop by one lax.scan of K time-gated steps per trip — the step
        # body traces ONCE, so the chunk's static launch count covers K
        # steps. The overlapped schedule keeps K=1: its double-buffered
        # exchange pipeline is its own cross-step fusion.
        kfuse = _dispatch.resolve_chunk_fuse(
            param, "ns2d_dist_chunk_fuse", chunk,
            why_not=("overlapped chunk carries its own cross-step "
                     "exchange pipeline") if overlap else None)
        # per-tier exchange depth (tpu_exchange_depth axis=H): the dcn
        # axis's u/v strips come from ONE depth-H capture per H scan
        # steps (parallel/comm.capture_axis_strips) — explicit opt-in,
        # relaxed parity (staleness bounded by the block)
        depth_why = None
        if fused_k is None:
            depth_why = "needs the fused deep-halo step (tpu_fuse_phases)"
        elif self.ragged:
            depth_why = "ragged decomposition"
        elif field_faults:
            depth_why = "PAMPI_FAULTS field faults armed"
        part_names = [n for n in comm.axis_names if comm.axis_size(n) > 1]
        part_ext = [
            {"j": jl, "i": il}[n] for n in part_names]
        depths = _dispatch.resolve_exchange_depth(
            param, "ns2d_dist_exchange_depth", kfuse, dict(comm.tiers),
            part_names, part_ext,
            FUSE_DEEP_HALO if fused_k is not None else 1,
            why_not=depth_why)
        dax, ddepth = next(iter(depths.items())) if depths else (None, 0)
        self._exchange_depths = depths

        def fuse_block_scan(c, kblock):
            """Advance the scan carry by kfuse gated steps: the plain
            K-scan, or — with a depth map armed — kfuse/H depth blocks,
            each capturing the slow axis's strips once and scanning H
            pasted steps."""
            if dax is None:
                c, _ = lax.scan(kblock(None), c, None, length=kfuse)
                return c
            from ..parallel.comm import capture_axis_strips

            def dblock(c, _):
                s = tuple(
                    capture_axis_strips(x, comm, dax, ddepth,
                                        FUSE_DEEP_HALO)
                    for x in (c[0], c[1]))
                c, _ = lax.scan(kblock(s), c, None, length=ddepth)
                return c, None

            c, _ = lax.scan(dblock, c, None, length=kfuse // ddepth)
            return c

        def chunk_kernel(u, v, p, t, nt):
            def cond(c):
                return jnp.logical_and(c[3] <= te, c[5] < chunk)

            if kfuse > 1:
                def kblock(strips):
                    skw = {} if strips is None else {"strips": strips}

                    def blk(c, _):
                        def live(c):
                            if use_cap:
                                u, v, p, t, nt, cap = c
                                return step_impl(u, v, p, t, nt, cap,
                                                 **skw)
                            u, v, p, t, nt = c
                            return step_impl(u, v, p, t, nt, **skw)

                        return lax.cond(c[3] <= te, live,
                                        lambda c: c, c), None

                    return blk

                def body(c):
                    sc = fuse_block_scan(c[:5] + c[6:], kblock)
                    return sc[:5] + (c[5] + kfuse,) + sc[5:]
            else:
                def body(c):
                    if use_cap:
                        u, v, p, t, nt, k, cap = c
                        u, v, p, t, nt, cap = step_impl(u, v, p, t, nt, cap)
                        return u, v, p, t, nt, k + 1, cap
                    u, v, p, t, nt, k = c
                    u, v, p, t, nt = step_impl(u, v, p, t, nt)
                    return u, v, p, t, nt, k + 1

            init = (u, v, p, t, nt, jnp.asarray(0, jnp.int32))
            if use_cap:
                # the budget resets to the full itermax per chunk
                # dispatch (external arity unchanged)
                init = init + (itermax_i,)
            out = lax.while_loop(cond, body, init)
            return out[0], out[1], out[2], out[3], out[4]

        def chunk_kernel_metrics(u, v, p, t, nt, m):
            # the telemetry twin: replicated f32 metrics scalars ride the
            # carry, packed into the in-band vector at the chunk boundary
            def cond(c):
                return jnp.logical_and(c[3] <= te, c[5] < chunk)

            if kfuse > 1:
                def kblock(strips):
                    skw = {} if strips is None else {"strips": strips}

                    def blk(c, _):
                        def live(c):
                            if use_cap:
                                (u, v, p, t, nt, res, it, dtv, um, vm,
                                 bad, cap) = c
                                (u, v, p, t, nt, res, it, dtv, um, vm,
                                 cap) = step_impl(u, v, p, t, nt, cap,
                                                  **skw)
                            else:
                                (u, v, p, t, nt, res, it, dtv, um, vm,
                                 bad) = c
                                (u, v, p, t, nt, res, it, dtv, um,
                                 vm) = step_impl(u, v, p, t, nt, **skw)
                            # POST-step nt: the divergence record names
                            # the true step inside the K-block
                            res, it, dtv, um, vm, bad = _tm.metrics_step(
                                bad, nt, res, it, dtv, um, vm)
                            out = (u, v, p, t, nt, res, it, dtv, um, vm,
                                   bad)
                            return out + ((cap,) if use_cap else ())

                        return lax.cond(c[3] <= te, live,
                                        lambda c: c, c), None

                    return blk

                def body(c):
                    sc = fuse_block_scan(c[:5] + c[6:], kblock)
                    return sc[:5] + (c[5] + kfuse,) + sc[5:]
            else:
                def body(c):
                    if use_cap:
                        (u, v, p, t, nt, k, res, it, dtv, um, vm, bad,
                         cap) = c
                        u, v, p, t, nt, res, it, dtv, um, vm, cap = step_impl(
                            u, v, p, t, nt, cap)
                    else:
                        u, v, p, t, nt, k, res, it, dtv, um, vm, bad = c
                        u, v, p, t, nt, res, it, dtv, um, vm = step_impl(
                            u, v, p, t, nt
                        )
                    res, it, dtv, um, vm, bad = _tm.metrics_step(
                        bad, nt, res, it, dtv, um, vm)
                    out = (u, v, p, t, nt, k + 1, res, it, dtv, um, vm, bad)
                    return out + ((cap,) if use_cap else ())

            init = (u, v, p, t, nt, jnp.asarray(0, jnp.int32),
                    m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT],
                    m[_tm.M_UMAX], m[_tm.M_VMAX], m[_tm.M_BAD])
            if use_cap:
                init = init + (itermax_i,)
            out = lax.while_loop(cond, body, init)
            (u, v, p, t, nt, _k, res, it, dtv, um, vm, bad) = out[:12]
            return u, v, p, t, nt, _tm.metrics_pack(
                res, it, dtv, um, vm, 0.0, bad)

        if overlap:
            # the overlapped chunk: one prologue exchange fills the first
            # generation of the double buffer (per CHUNK dispatch, off
            # the per-step path); the loop carries (ud, vd, um, vm, gen)
            # internally — the chunk's EXTERNAL state arity is unchanged,
            # so checkpoints, recovery and every tool keep working
            def chunk_kernel_overlap(u, v, p, t, nt):
                ud, vd = exchange_buffers(u, v)
                um, vm = buffer_maxima(ud, vd)

                def cond(c):
                    return jnp.logical_and(c[3] <= te, c[5] < chunk)

                def body(c):
                    if use_cap:
                        u, v, p, t, nt, k, ud, vd, um, vm, gen, cap = c
                        (u, v, p, t, nt, ud, vd, um, vm, gen,
                         _res, _it, _dt, cap) = step_overlap(
                            u, v, p, t, nt, ud, vd, um, vm, gen, cap)
                        return (u, v, p, t, nt, k + 1, ud, vd, um, vm,
                                gen, cap)
                    u, v, p, t, nt, k, ud, vd, um, vm, gen = c
                    (u, v, p, t, nt, ud, vd, um, vm, gen,
                     _res, _it, _dt) = step_overlap(
                        u, v, p, t, nt, ud, vd, um, vm, gen)
                    return u, v, p, t, nt, k + 1, ud, vd, um, vm, gen

                init = (u, v, p, t, nt, jnp.asarray(0, jnp.int32),
                        ud, vd, um, vm, nt)
                if use_cap:
                    init = init + (itermax_i,)
                out = lax.while_loop(cond, body, init)
                return out[0], out[1], out[2], out[3], out[4]

            def chunk_kernel_overlap_metrics(u, v, p, t, nt, m):
                ud, vd = exchange_buffers(u, v)
                um, vm = buffer_maxima(ud, vd)

                def cond(c):
                    return jnp.logical_and(c[3] <= te, c[5] < chunk)

                def body(c):
                    if use_cap:
                        (u, v, p, t, nt, k, ud, vd, um, vm, gen,
                         res, it, dtv, mum, mvm, bad, cap) = c
                        (u, v, p, t, nt, ud, vd, um, vm, gen,
                         res, it, dtv, cap) = step_overlap(
                            u, v, p, t, nt, ud, vd, um, vm, gen, cap)
                    else:
                        (u, v, p, t, nt, k, ud, vd, um, vm, gen,
                         res, it, dtv, mum, mvm, bad) = c
                        (u, v, p, t, nt, ud, vd, um, vm, gen,
                         res, it, dtv) = step_overlap(
                            u, v, p, t, nt, ud, vd, um, vm, gen)
                    res, it, dtv, mum, mvm, bad = _tm.metrics_step(
                        bad, nt, res, it, dtv, um, vm)
                    out = (u, v, p, t, nt, k + 1, ud, vd, um, vm, gen,
                           res, it, dtv, mum, mvm, bad)
                    return out + ((cap,) if use_cap else ())

                init = (u, v, p, t, nt, jnp.asarray(0, jnp.int32),
                        ud, vd, um, vm, nt,
                        m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT],
                        m[_tm.M_UMAX], m[_tm.M_VMAX], m[_tm.M_BAD])
                if use_cap:
                    init = init + (itermax_i,)
                out = lax.while_loop(cond, body, init)
                (u, v, p, t, nt, _k, _ud, _vd, _um, _vm, _gen,
                 res, it, dtv, mum, mvm, bad) = out[:17]
                return u, v, p, t, nt, _tm.metrics_pack(
                    res, it, dtv, mum, mvm, 0.0, bad)

        def init_kernel():
            shape = (jl + 2, il + 2)
            u = jnp.full(shape, param.u_init, dtype)
            v = jnp.full(shape, param.v_init, dtype)
            p = jnp.full(shape, param.p_init, dtype)
            return u, v, p

        spec = P("j", "i")
        self._debug_sm = jax.jit(
            comm.shard_map(
                step_phases,
                in_specs=(spec, spec, spec, P()),
                out_specs=(spec,) * 6 + (P(), P(), P()),
                check_vma=not pallas_q,
            )
        )
        self._init_sm = jax.jit(
            comm.shard_map(init_kernel, in_specs=(), out_specs=(spec,) * 3)
        )
        mextra = (P(),) if metrics else ()
        if overlap:
            chunk_fn = (chunk_kernel_overlap_metrics if metrics
                        else chunk_kernel_overlap)
        else:
            chunk_fn = chunk_kernel_metrics if metrics else chunk_kernel
        self._chunk_sm = jax.jit(
            comm.shard_map(
                chunk_fn,
                in_specs=(spec, spec, spec, P(), P()) + mextra,
                out_specs=(spec, spec, spec, P(), P()) + mextra,
                check_vma=not pallas_q,
            )
        )
        _tm.emit("build", family="ns2d_dist",
                 grid=[self.jmax, self.imax], mesh=list(comm.dims),
                 trace_wall_s=round(time.perf_counter() - self._t0_build, 3),
                 phases=_dispatch.last("ns2d_dist_phases"))
        # static per-shard halo-exchange byte counts (the step-level
        # exchanges of the path actually dispatched; the pressure
        # solve's internal exchanges depend on CA depth/iteration count
        # and are excluded). Built unconditionally: the telemetry `halo`
        # record and the commcheck trace census (analysis/commcheck.py)
        # read the SAME dict, both priced by comm.halo_exchange_bytes.
        isz = jnp.dtype(dtype).itemsize
        rec = {
            "family": "ns2d_dist", "mesh": list(comm.dims),
            "shard": [jl, il], "dtype": str(jnp.dtype(dtype)),
            "path": "fused" if fused_k is not None else "jnp",
            "exchange_bytes_depth1":
                halo_exchange_bytes((jl, il), 1, isz),
        }
        if fused_k is not None:
            from ..ops.ns2d_fused import fused_deep_layout_2d

            fbr, _fh, fwp, fnb = fused_deep_layout_2d(
                jl, il, dtype, FUSE_DEEP_HALO - 1)
            full_cells = fnb * fbr * fwp
            rec.update(
                deep_halo=FUSE_DEEP_HALO,
                deep_exchange_bytes=halo_exchange_bytes(
                    (jl, il), FUSE_DEEP_HALO, isz),
                exchanges_per_step={"deep": 2},
                # the per-step PRE grid sweep (swept padded cells):
                # 1x full serial, 2x full for the PR 8 split halves,
                # the banded plan's sum when grid-restricted — the
                # BENCH/smoke metric the restriction is judged by
                pre_grid_cells=full_cells,
            )
            if self._exchange_depths:
                # per-tier depth map (ISSUE 17): the mapped dcn axis's
                # per-step strips are replaced by ONE depth-H capture
                # pair per H-step block — `exchanges_per_step["deep"]`
                # then covers the UNMAPPED (ici) axes only, and the
                # block-amortized capture rides exchanges_per_block.
                # The byte helpers (comm.exchange_schedule_*bytes) and
                # the commcheck census both read these four keys.
                rec.update(
                    exchange_depths=dict(self._exchange_depths),
                    depth_block=max(self._exchange_depths.values()),
                    exchanges_per_block={"deep": 2},
                    axes=list(comm.axis_names),
                )
            if overlap:
                # same per-step schedule (2 deep exchanges), but posted
                # at the end of the step into the double buffer; the
                # chunk prologue fills the first generation — commcheck's
                # census cross-check counts both classes
                rec.update(path="fused_overlap",
                           overlap="double_buffered",
                           exchanges_per_chunk={"deep": 2},
                           pre_grid_cells=(
                               self._overlap_plan["cells"]
                               if self._overlap_plan is not None
                               else 2 * full_cells),
                           pre_grid_cells_full=2 * full_cells)
        else:
            rec.update(exchanges_per_step={
                "depth1": 4 + (2 if gmasks is not None else 0),
                "shift": 2,
            })
        # hierarchical-exchange accounting (ROADMAP item 3): the axis->
        # tier map and the per-step DCN-tier bytes — 0 on single-tier
        # meshes, the first-class slow-fabric BENCH metric on a
        # multi-slice pod (tools/bench_trend.py gates it downward)
        from ..parallel.comm import exchange_schedule_tier_bytes

        rec["tier_map"] = dict(comm.tiers)
        rec["dcn_exchange_bytes"] = exchange_schedule_tier_bytes(
            comm, rec).get("dcn", 0)
        self._halo_rec = rec
        if _tm.enabled():
            _tm.emit("halo", **rec)

    # ------------------------------------------------------------------
    def _halo_record(self) -> dict:
        """The static halo-exchange accounting of the path this build
        dispatched — the dict the telemetry `halo` record emits, exposed
        so analysis/commcheck.py can cross-check it against the traced
        collective census without arming PAMPI_TELEMETRY (which would
        change the traced program)."""
        return dict(self._halo_rec)

    def _rebuild_chunk(self):
        """Rebuild every traced kernel against the solver's CURRENT
        attributes (recovery dt clamp) — the rollback-recovery rebuild hook
        (models/_driver.RingRecovery). Advances the fault-injection
        generation (see models/ns2d._rebuild_chunk)."""
        self._field_faults = _fi.take_field_faults()
        self._build()
        return self._chunk_sm

    def initial_state(self) -> tuple:
        """(u, v, p, t, nt[, metrics]) matching the built chunk's arity
        (the NS-2D convention — see models/ns2d.initial_state)."""
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        state = (self.u, self.v, self.p,
                 jnp.asarray(self.t, time_dtype),
                 jnp.asarray(self.nt, jnp.int32))
        if self._metrics:
            state = state + (_tm.metrics_init(),)
        return state

    def run(self, progress: bool = True, on_sync=None) -> None:
        """The dist drive loop now IS models/_driver.drive_chunks (PR 4):
        same chunk semantics as before (dispatch, read t, sync — the
        historical while-t<=te loop), plus the shared failure protocol the
        single-device families already had — transient-fault retry with a
        replenishing budget and divergence rollback-recovery when a ring
        is armed. No pallas rebuild hook here (the per-shard kernels have
        no per-backend rebuild path), so non-transient chunk failures
        propagate unchanged."""
        from ._driver import coord_ckpt_cadence, drive_chunks, make_recovery

        bar = Progress(self.param.te, enabled=progress and not _flags.verbose())
        state = self.initial_state()
        rec = (_tm.ChunkRecorder("ns2d_dist", self.nt)
               if self._metrics else None)
        recover = make_recovery(self, "ns2d_dist", time_index=3,
                                recorder=rec)

        def publish(s):
            self.u, self.v, self.p = s[0], s[1], s[2]
            self.t, self.nt = float(s[3]), int(s[4])

        def on_state(s):
            if rec is not None:
                rec.update(float(s[3]), int(s[4]), s[5])
            if recover is not None:
                recover.capture(s)
            if on_sync is not None:
                publish(s)
                on_sync(self)

        if recover is not None:
            recover.capture(state)  # first-chunk divergence is recoverable
        # multi-process transient retry rides the chunk-boundary agreement
        # protocol (parallel/coordinator.py): every rank takes the same
        # retry/rollback/checkpoint decision from the allgathered fault
        # word, so the PR 4 single-controller ban (transient_budget=0 —
        # a rank-local re-dispatch would desynchronize collectives) is
        # lifted whenever the coordinator is armed. tpu_coord off
        # restores the ban: a fault kills the job cleanly.
        from ..parallel.coordinator import make_coordinator

        coord = make_coordinator(self.param, "ns2d_dist")
        budget = 1 if (coord is not None or jax.process_count() == 1) else 0
        ckpt_every, on_ckpt = coord_ckpt_cadence(self, coord, publish)
        # PAMPI_XPROF: device-trace the drive loop (no-op when unset);
        # the step count rides the xprof record so report tooling can
        # normalize device times per step
        nt0 = self.nt
        with _xprof.capture("ns2d_dist", steps=lambda: self.nt - nt0):
            state = drive_chunks(
                state, self._chunk_sm, self.param.te, 3, bar,
                retry=lambda: None, on_state=on_state,
                replenish_after=self.param.tpu_retry_replenish,
                recover=recover, transient_budget=budget,
                coordinator=coord, ckpt_every=ckpt_every,
                on_ckpt=on_ckpt, family="ns2d_dist",
                ledger=getattr(self, "_fault_ledger", None))
            publish(state)
        self._emit_exchange_span()

    def _emit_exchange_span(self) -> None:
        """The ROADMAP-mandated `exchange` span: the serial critical-path
        cost of one step's declared halo schedule, measured on an
        exchange-only program (parallel/comm.time_exchange_ms) AFTER the
        drive loop so the probe dispatches never pollute chunk timings or
        the captured trace. Together with the xprof record's exchange
        device/exposed split this is the comm-hidden-fraction input
        (tools/telemetry_report.comm_hidden_fraction)."""
        if not _tm.enabled():
            return
        from ..parallel.comm import exchange_schedule_bytes, time_exchange_ms

        rec = self._halo_record()
        _tm.emit_span(
            f"{rec['family']}.exchange",
            time_exchange_ms(self.comm, rec),
            path=rec["path"], mesh=rec["mesh"], shard=rec["shard"],
            bytes_per_step=exchange_schedule_bytes(rec),
            mode="serial_probe")

    # -- collect: stacked extended blocks -> full reference-layout array -
    def _assemble(self, stacked) -> np.ndarray:
        """Rebuild the (jmax+2, imax+2) array from stacked extended blocks:
        interiors everywhere, ghost strips taken from wall shards
        (≙ commCollectResult's ghost-strip + assembly, comm.c:246-427)."""
        arr = self.comm.collect(stacked)  # multihost-safe host gather
        Pj, Pi = self.comm.dims
        jl, il = self.jl, self.il
        # assemble at the PADDED global shape, crop the dead tail at the end
        # (identity when divisible); the global ghost ring rows/cols land in
        # block interiors when ragged, so the crop keeps them
        full = np.zeros((Pj * jl + 2, Pi * il + 2))
        for cj in range(Pj):
            for ci in range(Pi):
                b = arr[
                    cj * (jl + 2) : (cj + 1) * (jl + 2),
                    ci * (il + 2) : (ci + 1) * (il + 2),
                ]
                full[1 + cj * jl : 1 + (cj + 1) * jl, 1 + ci * il : 1 + (ci + 1) * il] = b[
                    1:-1, 1:-1
                ]
                if cj == 0:
                    full[0, 1 + ci * il : 1 + (ci + 1) * il] = b[0, 1:-1]
                if cj == Pj - 1:
                    full[-1, 1 + ci * il : 1 + (ci + 1) * il] = b[-1, 1:-1]
                if ci == 0:
                    full[1 + cj * jl : 1 + (cj + 1) * jl, 0] = b[1:-1, 0]
                if ci == Pi - 1:
                    full[1 + cj * jl : 1 + (cj + 1) * jl, -1] = b[1:-1, -1]
                if cj == 0 and ci == 0:
                    full[0, 0] = b[0, 0]
                if cj == 0 and ci == Pi - 1:
                    full[0, -1] = b[0, -1]
                if cj == Pj - 1 and ci == 0:
                    full[-1, 0] = b[-1, 0]
                if cj == Pj - 1 and ci == Pi - 1:
                    full[-1, -1] = b[-1, -1]
        return full[: self.jmax + 2, : self.imax + 2]

    def fields(self):
        return self._assemble(self.u), self._assemble(self.v), self._assemble(self.p)

    # -- elastic-checkpoint contract (utils/checkpoint.save_elastic) ---
    def global_shape(self) -> tuple:
        return (self.jmax + 2, self.imax + 2)

    def global_fields(self) -> dict:
        """MESH-INDEPENDENT reference-layout globals: same assembly as
        `_assemble` (interiors everywhere, ghost ring from wall shards)
        through the shared dtype-preserving N-D helper — what makes an
        elastic checkpoint restorable on a DIFFERENT mesh. Collective
        under a multi-process launch (CartComm.collect)."""
        from ..utils.checkpoint import assemble_global

        return {
            f: assemble_global(
                self.comm.collect(getattr(self, f)), self.comm.dims,
                (self.jl, self.il), (self.jmax, self.imax))
            for f in ("u", "v", "p")
        }

    def set_global_fields(self, fields: dict) -> None:
        """The elastic-restore resharding step: re-block the global
        array for THIS solver's mesh and place it on the solver's own
        NamedSharding — the saved mesh never constrains the target."""
        from ..utils.checkpoint import scatter_blocks

        for f, arr in fields.items():
            cur = getattr(self, f)
            stacked = scatter_blocks(
                np.asarray(arr), self.comm.dims, (self.jl, self.il))
            new = jnp.asarray(stacked, cur.dtype)
            sh = getattr(cur, "sharding", None)
            if sh is not None:
                new = jax.device_put(new, sh)
            setattr(self, f, new)

    def write_result(
        self, pressure_path: str = "pressure.dat", velocity_path: str = "velocity.dat"
    ) -> None:
        # fields() gathers collectively — all processes join; rank 0 writes
        u, v, p = self.fields()
        if self.comm.is_master:
            write_pressure(p, self.dx, self.dy, pressure_path)
            write_velocity(u, v, self.dx, self.dy, velocity_path)
