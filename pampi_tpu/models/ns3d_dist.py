"""Distributed NS-3D over a 3-D ("k","j","i") device mesh.

This COMPLETES the capability assignment-6 hands out as a skeleton: the
reference's `comm.c` ships its `_MPI` bodies unfinished (`// fill`,
comm.c:124-239,479-483), so the 3-D Cartesian-decomposed solver never runs
distributed in the reference tree. Here the full 3-D choreography runs over
the mesh comm layer (halo_exchange = 6-face ppermute, halo_shift = staggered
donor edges, psum/pmax reductions), with the same EXACT-sequential-parity
policy as NS-2D (see models/ns2d_dist.py): halos refreshed before every
cross-shard read makes the distributed trajectory equal the single-device
solver bitwise (mod reduction order) on any mesh shape.

Exchange points per step (mirroring the reference's own calls where they
exist): u/v/w at step start (maxElement ghost parity), u/v/w after BCs
(≙ computeFG's commExchange, solver.c:635-637), F/G/H one-directional shift
before RHS (≙ commShift, solver.c:161), p once per n fused red-black
iterations at halo depth 2n (communication-avoiding; ≙ solve's per-pass
commExchange :208, traded latency-for-bandwidth the ICI way) and after the
solve loop (≙ trailing :288).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops import ns3d as ops
from .ns3d import sor_coefficients_3d, write_vtk_result
from ..parallel.comm import (
    master_print,
    CartComm,
    halo_exchange,
    halo_exchange_bytes,
    halo_shift,
    reduction,
)
from ..parallel.stencil2d import (
    ca_halo,
    ca_inner,
    ca_supported,
    embed_deep,
    strip_deep,
)
from ..parallel.octants_dist import (
    o_exchange,
    octants_dispatch,
    pack_ext_to_o,
    unpack_o_to_ext,
)
from ..parallel.stencil3d import (
    ca_masks_3d,
    ca_rb_iters_3d,
    face_flags,
    rb_exchange_per_sweep_3d,
)
from ..utils import dispatch as _dispatch
from ..utils import faultinject as _fi
from ..utils import flags as _flags
from ..utils import telemetry as _tm
from ..utils import xprof as _xprof
from ._driver import clamped_dt
from ..utils.grid import Grid
from ..utils.params import Parameter
from ..utils.precision import resolve_dtype
from ..utils.progress import Progress
from ..utils.vtkio import VtkWriter

NOSLIP, SLIP, OUTFLOW, PERIODIC = 1, 2, 3, 4


def _sel(pred, new, old):
    return jnp.where(pred, new, old)


class NS3DDistSolver:
    """Mesh-parallel NS-3D solver; same .par interface as NS3DSolver."""

    CHUNK = 32

    def __init__(self, param: Parameter, comm: CartComm | None = None, dtype=None):
        self._t0_build = time.perf_counter()
        # trace-time telemetry gate (utils/flags.py convention)
        metrics = _tm.enabled()
        self._metrics = metrics
        if dtype is None:
            dtype = resolve_dtype(param.tpu_dtype,
                                  record_key="ns3d_dist_dtype")
        self.param = param
        self.dtype = dtype
        self.comm = comm if comm is not None else CartComm(
            ndims=3, extents=(param.kmax, param.jmax, param.imax),
            tiers=param.tpu_mesh_tiers,
        )
        self.grid = Grid(
            imax=param.imax,
            jmax=param.jmax,
            kmax=param.kmax,
            xlength=param.xlength,
            ylength=param.ylength,
            zlength=param.zlength,
        )
        g = self.grid
        # ragged pad-with-mask decomposition (parallel/ragged3d.py): any
        # grid runs on any mesh (≙ sizeOfRank, assignment-6/src/comm.c:19-22)
        self.kl, self.jl, self.il = self.comm.local_shape(
            (g.kmax, g.jmax, g.imax), ragged=True
        )
        Pk, Pj, Pi = self.comm.dims
        self.ragged = (
            self.kl * Pk != g.kmax or self.jl * Pj != g.jmax
            or self.il * Pi != g.imax
        )
        param = _dispatch.resolve_solver(
            param, obstacles=bool(param.obstacles.strip()),
            ragged=self.ragged,
        )
        self.param = param
        # round 5 (VERDICT r4 item 2): obstacles compose with ragged
        # decompositions in 3-D too (the jnp CA path; the 3-D kernel stays
        # divisible-only — obstacle3d.make_dist_obstacle_solver_3d).
        # mg/fft stay divisible-only (coarsening/diagonalization need
        # exact extents).
        if self.ragged and param.tpu_solver in ("mg", "fft"):
            raise ValueError(
                f"tpu_solver {param.tpu_solver} needs a divisible grid/mesh "
                f"(grid {g.kmax}x{g.jmax}x{g.imax} on {self.comm.dims}); "
                "ragged pad-with-mask runs use tpu_solver sor (obstacles "
                "compose)"
            )
        inv_sqr_sum = 1.0 / g.dx**2 + 1.0 / g.dy**2 + 1.0 / g.dz**2
        self.dt_bound = 0.5 * param.re / inv_sqr_sum
        self.t = 0.0
        self.nt = 0
        # flag-field obstacles: GLOBAL static geometry; every shard slices
        # its mask blocks inside the kernel (ops/obstacle3d.shard_masks_3d)
        if param.obstacles.strip():
            if param.tpu_solver == "fft":
                raise ValueError(
                    "tpu_solver fft cannot solve obstacle flag fields (the "
                    "stencil is not constant-coefficient); use sor or mg"
                )
            from ..ops import obstacle3d as obst3

            fluid = obst3.build_fluid_3d(
                g.imax, g.jmax, g.kmax, g.dx, g.dy, g.dz, param.obstacles
            )
            self.masks = obst3.make_masks_3d(
                fluid, g.dx, g.dy, g.dz, param.omg, dtype
            )
        else:
            self.masks = None
        self._dt_scale = 1.0  # recovery dt clamp (models/_driver.clamped_dt)
        # fault-injection generation: taken here and in _rebuild_chunk
        # only (see models/ns2d.py for the rationale)
        self._field_faults = _fi.take_field_faults()
        self._build()
        self.u, self.v, self.w, self.p = self._init_sm()

    # ------------------------------------------------------------------
    def _build(self):
        comm = self.comm
        param = self.param
        g = self.grid
        dtype = self.dtype
        metrics = self._metrics  # trace-time telemetry gate (see __init__)
        # field-fault injection + recovery dt clamp: both trace-time, both
        # identity when unarmed (the PAMPI_FAULTS-unset jaxpr contract);
        # the generation is taken by __init__/_rebuild_chunk, not here
        field_faults = self._field_faults
        dt_scale = self._dt_scale
        kl, jl, il = self.kl, self.jl, self.il
        dx, dy, dz = g.dx, g.dy, g.dz

        bcs = {
            "top": param.bcTop,
            "bottom": param.bcBottom,
            "left": param.bcLeft,
            "right": param.bcRight,
            "front": param.bcFront,
            "back": param.bcBack,
        }
        problem = param.name.replace("3d", "")

        # -- wall-gated BCs (≙ commIsBoundary-guarded face loops) --------
        def set_bcs_divisible(u, v, w):
            return ops.set_boundary_conditions_3d(
                u, v, w, bcs, flags=face_flags(comm)
            )

        def set_special_bc_divisible(u):
            flags = face_flags(comm)
            if problem == "dcavity":
                # lid plane u[k, jl+1, i], global k in 1..kmax-1, i in
                # 1..imax-1: exclude last interior k/i on the hi-wall shards
                # (reference loop-bound quirk, solver.c:587-594)
                kmask = jnp.zeros(kl + 2, dtype).at[1:-1].set(1.0)
                kmask = kmask.at[-2].mul(1.0 - flags["back"].astype(dtype))
                imask = jnp.zeros(il + 2, dtype).at[1:-1].set(1.0)
                imask = imask.at[-2].mul(1.0 - flags["right"].astype(dtype))
                m2 = kmask[:, None] * imask[None, :]
                lid = 2.0 - u[:, -2, :]
                new_plane = jnp.where(m2 > 0, lid, u[:, -1, :])
                u = u.at[:, -1, :].set(_sel(flags["top"], new_plane, u[:, -1, :]))
            elif problem == "canal":
                cur = u[:, :, 0]
                new_plane = cur.at[1:-1, 1:-1].set(2.0)
                u = u.at[:, :, 0].set(_sel(flags["left"], new_plane, cur))
            return u

        def fgh_fixups_divisible(f, g_, h, u, v, w):
            flags = face_flags(comm)
            f = f.at[1:-1, 1:-1, 0].set(
                _sel(flags["left"], u[1:-1, 1:-1, 0], f[1:-1, 1:-1, 0])
            )
            f = f.at[1:-1, 1:-1, -2].set(
                _sel(flags["right"], u[1:-1, 1:-1, -2], f[1:-1, 1:-1, -2])
            )
            g_ = g_.at[1:-1, 0, 1:-1].set(
                _sel(flags["bottom"], v[1:-1, 0, 1:-1], g_[1:-1, 0, 1:-1])
            )
            g_ = g_.at[1:-1, -2, 1:-1].set(
                _sel(flags["top"], v[1:-1, -2, 1:-1], g_[1:-1, -2, 1:-1])
            )
            h = h.at[0, 1:-1, 1:-1].set(
                _sel(flags["front"], w[0, 1:-1, 1:-1], h[0, 1:-1, 1:-1])
            )
            h = h.at[-2, 1:-1, 1:-1].set(
                _sel(flags["back"], w[-2, 1:-1, 1:-1], h[-2, 1:-1, 1:-1])
            )
            return f, g_, h

        # -- ragged pad-with-mask wall handling (parallel/ragged3d.py) ---
        if self.ragged:
            from ..parallel import ragged3d as rg3

            def set_bcs(u, v, w):
                return rg3.set_bcs_3d_ragged(
                    u, v, w, bcs, comm, kl, jl, il, g.kmax, g.jmax, g.imax
                )

            def set_special_bc(u):
                return rg3.set_special_bc_3d_ragged(
                    u, problem, comm, kl, jl, il, g.kmax, g.jmax, g.imax
                )

            def fgh_fixups(f, g_, h, u, v, w):
                return rg3.fgh_fixups_ragged(
                    f, g_, h, u, v, w, comm, kl, jl, il,
                    g.kmax, g.jmax, g.imax,
                )
        else:
            set_bcs = set_bcs_divisible
            set_special_bc = set_special_bc_divisible
            fgh_fixups = fgh_fixups_divisible

        # -- pressure solve --------------------------------------------
        factor, idx2, idy2, idz2 = sor_coefficients_3d(dx, dy, dz, param.omg)
        epssq = param.eps * param.eps
        norm = float(g.imax * g.jmax * g.kmax)

        def _solve_sor(p, rhs, cap=None):
            """Communication-avoiding red-black solve (stencil3d.ca_*): one
            depth-2n halo exchange per n exact local iterations, n clamped by
            the shard extents (tpu_ca_inner; n=1 still halves the per-
            iteration message count vs exchange-per-half-sweep while keeping
            the trajectory identical). Shards with an extent of 1 cannot ship
            depth-2 strips from owned cells — they use the classic
            exchange-per-half-sweep fallback. `cap` is the residual-adaptive
            budget (tpu_itermax_adaptive); None = the historical trace."""
            limit = param.itermax if cap is None else cap
            supported = ca_supported(kl, jl, il)
            n = ca_inner(param, kl, jl, il) if supported else 1
            H = ca_halo(n, ragged=self.ragged) if supported else 1
            masks = ca_masks_3d(kl, jl, il, H, g.kmax, g.jmax, g.imax, dtype)
            pd = embed_deep(p, H)
            rd = halo_exchange(embed_deep(rhs, H), comm, depth=H)

            def cond(c):
                return jnp.logical_and(c[1] >= epssq, c[2] < limit)

            def body(c):
                pd, _, it = c
                if supported:
                    pd = halo_exchange(pd, comm, depth=H)
                    pd, r2 = ca_rb_iters_3d(
                        pd, rd, n, masks, factor, idx2, idy2, idz2
                    )
                else:
                    pd, r2 = rb_exchange_per_sweep_3d(
                        pd, rd, masks, comm, factor, idx2, idy2, idz2,
                        ragged=self.ragged,
                    )
                res = reduction(r2, comm, "sum") / norm
                if _flags.debug():
                    master_print(comm, "{} Residuum: {}", it + (n - 1), res)
                return pd, res, it + n

            pd, res, it = lax.while_loop(
                cond, body,
                (pd, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32)),
            )
            return halo_exchange(strip_deep(pd, H), comm), res, it

        def _solve_sor_split(p, rhs, cap=None):
            """The sweep-split twin of _solve_sor (dispatched with the
            overlapped schedule — see models/ns2d_dist._solve_sor_split):
            same n-iteration residual cadence, bitwise the CA
            trajectory, every depth-1 exchange posted behind an
            interior update (stencil3d.rb_split_iter_3d)."""
            from ..parallel import overlap as _ovl
            from ..parallel.comm import persistent_exchange
            from ..parallel.stencil3d import rb_split_iter_3d

            limit = param.itermax if cap is None else cap
            supported = ca_supported(kl, jl, il)
            n = ca_inner(param, kl, jl, il) if supported else 1
            masks = ca_masks_3d(kl, jl, il, 1, g.kmax, g.jmax, g.imax,
                                dtype)
            int_mask = _ovl.interior_mask(
                (kl, jl, il), 2,
                partitioned=tuple(d > 1 for d in comm.dims))
            sched1 = persistent_exchange(comm, 1, dtype)

            def cond(c):
                return jnp.logical_and(c[1] >= epssq, c[2] < limit)

            def body(c):
                p, _, it = c
                r2 = None
                for _k in range(n):
                    p, r2 = rb_split_iter_3d(
                        p, rhs, masks, sched1, int_mask, factor, idx2,
                        idy2, idz2, ragged=self.ragged)
                res = reduction(r2, comm, "sum") / norm
                if _flags.debug():
                    master_print(comm, "{} Residuum: {}", it + (n - 1), res)
                return p, res, it + n

            p, res, it = lax.while_loop(
                cond, body,
                (p, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32)),
            )
            return halo_exchange(p, comm), res, it

        # -- octant-layout production pressure solve (the round-3 wiring of
        # the 4.9x/iteration octant kernel into the distributed path; same
        # dispatch contract as models/ns2d_dist's quarters) ---------------
        plain_sor = param.tpu_solver not in ("mg", "fft") and self.masks is None
        rb_o, og, n_o, pallas_o = octants_dispatch(
            param, g.kmax, g.jmax, g.imax, kl, jl, il, dx, dy, dz, dtype,
            "ns3d_dist", plain_sor=plain_sor and not self.ragged,
            dims=comm.dims,
        )
        if rb_o is None:
            tag = (
                "jnp_ca" if plain_sor else f"other_{param.tpu_solver}"
                if self.masks is None else "obstacle_jnp"
            )
            if self.ragged:
                tag += " ragged"
            _dispatch.record("ns3d_dist", tag)
        self._pallas_o = pallas_o

        def _solve_sor_octants(p, rhs, cap=None):
            """Stacked-octant CA solve on the halo-1 extended blocks; returns
            the exchanged halo-1 block like _solve_sor (adaptUVW reads p
            across shard edges, ≙ the trailing commExchange solver.c:288)."""
            from ..parallel.comm import get_offsets

            limit = param.itermax if cap is None else cap
            koff = get_offsets("k", kl)
            joff = get_offsets("j", jl)
            ioff = get_offsets("i", il)
            qoffs = jnp.stack([
                (koff // 2).astype(jnp.int32),
                (joff // 2).astype(jnp.int32),
                (ioff // 2).astype(jnp.int32),
            ])
            ro = o_exchange(pack_ext_to_o(rhs, og), comm, og)
            xo = pack_ext_to_o(p, og)

            def cond(c):
                return jnp.logical_and(c[1] >= epssq, c[2] < limit)

            def body(c):
                xo, _, it = c
                xo = o_exchange(xo, comm, og)
                xo, r2 = rb_o(qoffs, xo, ro)
                res = reduction(r2, comm, "sum") / norm
                if _flags.debug():
                    master_print(comm, "{} Residuum: {}", it + (n_o - 1), res)
                return xo, res, it + n_o

            xo, res, it = lax.while_loop(
                cond, body,
                (xo, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32)),
            )
            return halo_exchange(unpack_o_to_ext(xo, og), comm), res, it

        # pre-resolution of the overlap knob for the solve builders (see
        # models/ns2d_dist.py — selects the sweep-split smoother forms,
        # bitwise the serial forms either way; statically-known
        # ineligibility mirrored, fused-probe failure healed by the
        # serial MG rebuild at the sweep_split record)
        ovl_pre = (param.tpu_overlap != "off"
                   and not field_faults
                   and param.tpu_fuse_phases != "off"
                   and (param.tpu_overlap == "on"
                        or jax.default_backend() == "tpu"))
        mg_serial_rebuild = None
        if param.tpu_solver == "fft":
            from ..ops.dctpoisson import make_dist_dct_solve_3d

            solve = make_dist_dct_solve_3d(
                comm, g.imax, g.jmax, g.kmax, kl, jl, il, dx, dy, dz, dtype
            )
        elif param.tpu_solver == "mg":
            if self.masks is not None:
                # 3-D obstacle multigrid on a mesh (round 4)
                from ..ops.multigrid import make_dist_obstacle_mg_solve_3d

                solve, mg_pallas = make_dist_obstacle_mg_solve_3d(
                    comm, g.imax, g.jmax, g.kmax, kl, jl, il, dx, dy, dz,
                    param.eps, param.itermax, self.masks, dtype,
                    stall_rtol=param.tpu_mg_stall_rtol,
                    fused=param.tpu_mg_fused,
                )
                # the MG factory reports per-shard Pallas smoothing:
                # relax check_vma (the obstacle-solver contract)
                pallas_o = pallas_o or mg_pallas
                self._pallas_o = pallas_o
            else:
                from ..ops.multigrid import make_dist_mg_solve_3d

                solve, mg_pallas = make_dist_mg_solve_3d(
                    comm, g.imax, g.jmax, g.kmax, kl, jl, il, dx, dy, dz,
                    param.eps, param.itermax, dtype,
                    stall_rtol=param.tpu_mg_stall_rtol, split=ovl_pre,
                    fused=param.tpu_mg_fused,
                )
                pallas_o = pallas_o or mg_pallas
                self._pallas_o = pallas_o
                if ovl_pre:
                    def mg_serial_rebuild():
                        s2, _ = make_dist_mg_solve_3d(
                            comm, g.imax, g.jmax, g.kmax, kl, jl, il,
                            dx, dy, dz, param.eps, param.itermax, dtype,
                            stall_rtol=param.tpu_mg_stall_rtol,
                            split=False, fused=param.tpu_mg_fused,
                        )
                        return s2
        elif self.masks is not None:
            from ..ops.obstacle3d import make_dist_obstacle_solver_3d

            solve, obs_pallas = make_dist_obstacle_solver_3d(
                comm, g.imax, g.jmax, g.kmax, kl, jl, il, dx, dy, dz,
                param.eps, param.itermax, self.masks, dtype,
                ca_n=param.tpu_ca_inner, sor_inner=param.tpu_sor_inner,
                ragged=self.ragged,
            )
            # relax check_vma when the obstacle solver reports it
            # dispatched its per-shard Pallas kernel
            pallas_o = pallas_o or obs_pallas
            self._pallas_o = pallas_o
        elif rb_o is not None:
            solve = _solve_sor_octants
        else:
            solve = _solve_sor

        # -- fused step-phase kernels (ops/ns3d_fused.py): the per-shard
        # non-solve phases collapse into two global-coordinate-gated Pallas
        # kernels around the solve (PRE on the depth-H deep-halo block, POST
        # on the plain extended block) — the 3-D twin of the NS-2D wiring
        # (models/ns2d_dist.py). Ragged shards are the same kernels at
        # uneven block bounds (global gating + the POST live-mask multiply);
        # obstacle runs feed the per-shard global-constant flag slices at
        # call time (fluid=True).
        from ..ops.ns3d_fused import FUSE_DEEP_HALO, probe_fused_3d

        fuse_why_not = None
        if min(kl, jl, il) < FUSE_DEEP_HALO:
            fuse_why_not = f"shard extents < deep halo {FUSE_DEEP_HALO}"
        fused_k = None
        if _dispatch.resolve_fuse_phases(
            param, "auto", dtype, probe_fused_3d, "ns3d_dist_phases",
            why_not=fuse_why_not,
        ):
            from ..ops import ns3d_fused as nf3

            try:
                pre_k, pad_deep, unpad_deep, _hk = nf3.make_fused_pre_3d(
                    param, g.kmax, g.jmax, g.imax, dx, dy, dz, dtype,
                    kl=kl, jl=jl, il=il, ext_pad=FUSE_DEEP_HALO - 1,
                    fluid=True if self.masks is not None else None,
                )
                post_k, pad_ext, unpad_ext, _hk2 = nf3.make_fused_post_3d(
                    param, g.kmax, g.jmax, g.imax, dx, dy, dz, dtype,
                    kl=kl, jl=jl, il=il,
                    fluid=True if self.masks is not None else None,
                    ragged=self.ragged,
                )
                fused_k = (pre_k, post_k)
                pallas_o = True
                self._pallas_o = True
            except ValueError as exc:  # VMEM-infeasible shard geometry
                _dispatch.record("ns3d_dist_phases", f"jnp ({exc})")

        # -- comm/compute overlap: the 3-D twin of the NS-2D wiring (see
        # models/ns2d_dist.py — double-buffered deep blocks, split PRE,
        # carried CFL maxima; `off` stays bitwise the serial schedule)
        ovl_why = None
        if fused_k is None:
            ovl_why = "needs the fused deep-halo step (tpu_fuse_phases)"
        elif field_faults:
            ovl_why = ("PAMPI_FAULTS field faults armed (in-step writes "
                       "would postdate the posted exchange)")
        overlap = _dispatch.resolve_overlap(
            param, "overlap_ns3d_dist", why_not=ovl_why)
        self._overlap = overlap
        self._overlap_plan = None  # set by the overlap block when the
        #   grid-restricted halves dispatch (tpu_overlap_restrict)
        # sweep split (see models/ns2d_dist.py)
        if overlap and solve is _solve_sor:
            solve = _solve_sor_split
            _dispatch.record("sweep_split_ns3d_dist", "split (jnp rb-sor)")
        elif overlap and param.tpu_solver == "mg" and self.masks is None:
            _dispatch.record("sweep_split_ns3d_dist",
                             "split (mg jnp-smoother levels)")
        elif not overlap and mg_serial_rebuild is not None:
            # the pre-resolution guessed overlap but the fused probe
            # failed at build: drop the split smoother so the traced
            # program matches the recorded serial schedule
            solve = mg_serial_rebuild()
        elif overlap:
            _dispatch.record("sweep_split_ns3d_dist",
                             "serial (pallas/other solve)")

        # residual-adaptive itermax (see models/ns2d_dist.py): the cap
        # rides the chunk carry only, resets per chunk dispatch; dist
        # SOR paths only
        adapt_n = int(param.tpu_itermax_adaptive)
        use_cap = adapt_n > 0 and solve in (
            _solve_sor, _solve_sor_split, _solve_sor_octants)
        if adapt_n > 0:
            _dispatch.record(
                "itermax_adaptive_ns3d_dist",
                f"adaptive (+{adapt_n} slack)" if use_cap
                else "static (solve path carries no sweep budget)")
        itermax_i = jnp.asarray(param.itermax, jnp.int32)

        def next_cap(res, it):
            return jnp.where(res < epssq,
                             jnp.minimum(itermax_i, it + adapt_n),
                             itermax_i)

        gmasks = self.masks
        if gmasks is not None:
            from ..ops.obstacle3d import (
                adapt_uvw_obstacle,
                apply_obstacle_velocity_bc_3d,
                mask_fgh,
                shard_masks_3d,
            )

            # ragged ceil-division overhang (0 when divisible): HI-side
            # zero-pad so trailing-shard mask slices never clamp
            from ..parallel.stencil2d import ceil_overhang

            over_k = ceil_overhang(comm.axis_size("k"), kl, g.kmax)
            over_j = ceil_overhang(comm.axis_size("j"), jl, g.jmax)
            over_i = ceil_overhang(comm.axis_size("i"), il, g.imax)

            def local_masks():
                # must run INSIDE the shard_map trace (mesh offsets)
                return shard_masks_3d(gmasks, kl, jl, il,
                                      over_k, over_j, over_i)

            def fused_flag_blocks():
                """Per-shard deep-halo and extended slices of the global 0/1
                fluid flag for the fused kernels (the shard_masks_3d
                global-constant-slice convention), in the kernels' padded
                layouts — see models/ns2d_dist.py's twin for the invariants."""
                from ..parallel.comm import get_offsets

                H = FUSE_DEEP_HALO
                koff = get_offsets("k", kl)
                joff = get_offsets("j", jl)
                ioff = get_offsets("i", il)
                fl = gmasks.fluid
                wide = jnp.pad(fl, (
                    (H - 1, over_k + H - 1), (H - 1, over_j + H - 1),
                    (H - 1, over_i + H - 1),
                ))
                deep = lax.dynamic_slice(
                    wide, (koff, joff, ioff),
                    (kl + 2 * H, jl + 2 * H, il + 2 * H),
                )
                hi = jnp.pad(fl, ((0, over_k), (0, over_j), (0, over_i)))
                ext = lax.dynamic_slice(
                    hi, (koff, joff, ioff), (kl + 2, jl + 2, il + 2)
                )
                return pad_deep(deep), pad_ext(ext)

        def cfl_from_maxima(umax, vmax, wmax):
            # the scalar tail, shared with the overlapped step (whose
            # maxima ride the carry from the previous POST kernel)
            inf = jnp.asarray(jnp.inf, dtype)
            dt = jnp.minimum(
                jnp.asarray(self.dt_bound, dtype),
                jnp.minimum(
                    jnp.where(umax > 0, dx / umax, inf),
                    jnp.minimum(
                        jnp.where(vmax > 0, dy / vmax, inf),
                        jnp.where(wmax > 0, dz / wmax, inf),
                    ),
                ),
            )
            return dt * param.tau

        def compute_dt(u, v, w):
            umax = reduction(jnp.max(jnp.abs(u)), comm, "max")
            vmax = reduction(jnp.max(jnp.abs(v)), comm, "max")
            wmax = reduction(jnp.max(jnp.abs(w)), comm, "max")
            return cfl_from_maxima(umax, vmax, wmax)

        adaptive = param.tau > 0.0
        idx_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32

        def step(u, v, w, p, t, nt, cap=None):
            u, v, w, p = _fi.apply_field_faults(field_faults, nt, u=u, v=v,
                                                w=w, p=p)
            u = halo_exchange(u, comm)
            v = halo_exchange(v, comm)
            w = halo_exchange(w, comm)
            dt = compute_dt(u, v, w) if adaptive else jnp.asarray(param.dt, dtype)
            dt = clamped_dt(dt, dt_scale)
            u, v, w = set_bcs(u, v, w)
            u = set_special_bc(u)
            u = halo_exchange(u, comm)
            v = halo_exchange(v, comm)
            w = halo_exchange(w, comm)
            if gmasks is not None:
                # needs the fully-exchanged post-BC state (the single-device
                # op reads the whole array at once); its own halo-cell
                # outputs are refreshed by one more exchange
                u, v, w = apply_obstacle_velocity_bc_3d(u, v, w, local_masks())
                u = halo_exchange(u, comm)
                v = halo_exchange(v, comm)
                w = halo_exchange(w, comm)
            f, g_, h = ops.compute_fgh_interior(
                u, v, w, dt, param.re, param.gx, param.gy, param.gz,
                param.gamma, dx, dy, dz,
            )
            f, g_, h = fgh_fixups(f, g_, h, u, v, w)
            if gmasks is not None:
                f, g_, h = mask_fgh(f, g_, h, u, v, w, local_masks())
            f = halo_shift(f, comm, "i")
            g_ = halo_shift(g_, comm, "j")
            h = halo_shift(h, comm, "k")
            rhs = ops.compute_rhs(f, g_, h, dt, dx, dy, dz)
            p, res, it = (solve(p, rhs, cap) if cap is not None
                          else solve(p, rhs))

            def adapt(u, v, w):
                if gmasks is not None:
                    return adapt_uvw_obstacle(
                        u, v, w, f, g_, h, p, dt, dx, dy, dz, local_masks()
                    )
                return ops.adapt_uvw(u, v, w, f, g_, h, p, dt, dx, dy, dz)

            if not self.ragged:
                u, v, w = adapt(u, v, w)
            else:
                # ragged projection: only the true global interior updates;
                # interior-stored ghost planes keep their BC-era values and
                # dead cells are zeroed (see models/ns2d_dist.py). One
                # gating block for the plain AND obstacle projections.
                from ..parallel import ragged3d as rg3

                gk, gj, gi = rg3.global_index_grids(comm, kl, jl, il)
                interior = (
                    (gk >= 1) & (gk <= g.kmax)
                    & (gj >= 1) & (gj <= g.jmax)
                    & (gi >= 1) & (gi <= g.imax)
                )
                live = rg3.live_masks_3d(
                    comm, kl, jl, il, g.kmax, g.jmax, g.imax, dtype
                )
                ua, va, wa = adapt(u, v, w)
                u = jnp.where(interior, ua, u) * live
                v = jnp.where(interior, va, v) * live
                w = jnp.where(interior, wa, w) * live
            t_next = t + dt.astype(idx_dtype)
            if _flags.verbose():
                # printed AFTER t += dt, matching A6 main.c:58-62
                master_print(comm, "TIME {} , TIMESTEP {}", t_next, dt)
            capt = (next_cap(res, it),) if cap is not None else ()
            if metrics:
                # mesh-global maxima (replicated) — telemetry scalars
                um = reduction(jnp.max(jnp.abs(u)), comm, "max")
                vm = reduction(jnp.max(jnp.abs(v)), comm, "max")
                wm = reduction(jnp.max(jnp.abs(w)), comm, "max")
                return (u, v, w, p, t_next, nt + 1, res, it, dt,
                        um, vm, wm) + capt
            return (u, v, w, p, t_next, nt + 1) + capt

        def step_fused(u, v, w, p, t, nt, cap=None, strips=None):
            """The fused-phase twin of step() (see models/ns2d_dist.py):
            one deep exchange feeds the PRE kernel, the solve is unchanged,
            the POST kernel projects on the exchanged extended blocks.
            `strips` is the depth-scheduled variant (tpu_exchange_depth,
            see models/ns2d_dist.step_fused): the slow-tier axis pastes
            the K-block's captured strips instead of exchanging."""
            from ..parallel.comm import get_offsets

            pre_k, post_k = fused_k
            H = FUSE_DEEP_HALO
            u, v, w, p = _fi.apply_field_faults(field_faults, nt, u=u, v=v,
                                                w=w, p=p)
            if strips is None:
                ud = halo_exchange(embed_deep(u, H), comm, depth=H)
                vd = halo_exchange(embed_deep(v, H), comm, depth=H)
                wd = halo_exchange(embed_deep(w, H), comm, depth=H)
            else:
                from ..parallel.comm import paste_axis_strips

                (lo_u, hi_u), (lo_v, hi_v), (lo_w, hi_w) = strips
                ud = paste_axis_strips(
                    embed_deep(u, H), comm, dax, H, lo_u, hi_u)
                vd = paste_axis_strips(
                    embed_deep(v, H), comm, dax, H, lo_v, hi_v)
                wd = paste_axis_strips(
                    embed_deep(w, H), comm, dax, H, lo_w, hi_w)
            # ghost-inclusive CFL max over the deep blocks: same global
            # value set as the exchanged extended blocks
            dt = (compute_dt(ud, vd, wd) if adaptive
                  else jnp.asarray(param.dt, dtype))
            dt = clamped_dt(dt, dt_scale)
            offs = jnp.stack([
                get_offsets("k", kl), get_offsets("j", jl),
                get_offsets("i", il),
            ]).astype(jnp.int32)
            dt11 = jnp.full((1, 1), dt, dtype)
            pre_extra = post_extra = ()
            if gmasks is not None:
                flg_deep, flg_ext = fused_flag_blocks()
                pre_extra = (flg_deep,)
                post_extra = (flg_ext,)
            upd, vpd, wpd, fpd, gpd, hpd, rpd = pre_k(
                offs, dt11, pad_deep(ud), pad_deep(vd), pad_deep(wd),
                *pre_extra,
            )
            u = strip_deep(unpad_deep(upd), H)
            v = strip_deep(unpad_deep(vpd), H)
            w = strip_deep(unpad_deep(wpd), H)
            f = strip_deep(unpad_deep(fpd), H)
            g_ = strip_deep(unpad_deep(gpd), H)
            h = strip_deep(unpad_deep(hpd), H)
            rhs = strip_deep(unpad_deep(rpd), H)
            p, _res, _it = (solve(p, rhs, cap) if cap is not None
                            else solve(p, rhs))
            up, vp, wp, um_l, vm_l, wm_l = post_k(
                offs, dt11, pad_ext(u), pad_ext(v), pad_ext(w),
                pad_ext(f), pad_ext(g_), pad_ext(h), pad_ext(p),
                *post_extra,
            )
            u = unpad_ext(up)
            v = unpad_ext(vp)
            w = unpad_ext(wp)
            t_next = t + dt.astype(idx_dtype)
            if _flags.verbose():
                master_print(comm, "TIME {} , TIMESTEP {}", t_next, dt)
            capt = (next_cap(_res, _it),) if cap is not None else ()
            if metrics:
                # the POST kernel's maxima are per-shard: Allreduce MAX
                # makes them the global telemetry scalars
                um = reduction(um_l, comm, "max")
                vm = reduction(vm_l, comm, "max")
                wm = reduction(wm_l, comm, "max")
                return (u, v, w, p, t_next, nt + 1, _res, _it, dt,
                        um, vm, wm) + capt
            return (u, v, w, p, t_next, nt + 1) + capt

        if overlap:
            # -- overlapped fused step (parallel/overlap.py; see
            # models/ns2d_dist.py for the full invariants): the deep
            # exchange for step N+1 is posted after step N's POST and
            # carried double-buffered; PRE runs as interior (stale
            # blocks) + boundary (buffered exchanged blocks) halves
            # merged by the interior mask; dt from the carried maxima.
            from ..ops import ns3d_fused as nf3
            from ..ops.ns3d_fused import OVERLAP_RIM
            from ..parallel import overlap as _ovl
            from ..parallel.comm import get_offsets, persistent_exchange

            H3 = FUSE_DEEP_HALO
            deep_sched = persistent_exchange(comm, H3, dtype)
            # axis-aware rim + grid restriction over the leading k axis
            # (see models/ns2d_dist.py — same plan, k-plane bands)
            part3 = tuple(d > 1 for d in comm.dims)
            int_mask = _ovl.interior_mask((kl, jl, il), OVERLAP_RIM,
                                          partitioned=part3)
            bk_, _hh3, pw_, nbk_ = nf3.fused_deep_layout_3d(
                kl, jl, il, dtype, H3 - 1,
                masked=self.masks is not None)
            plan3 = _ovl.region_plan((kl, jl, il), OVERLAP_RIM, H3 - 1,
                                     bk_, nbk_, pw_, part3)
            restrict3 = _dispatch.resolve_overlap_restrict(
                param, "overlap_grid_ns3d_dist", plan3)
            self._overlap_plan = plan3 if restrict3 else None
            pre_int = pre_bnd = None
            if restrict3:
                fl_arg = True if self.masks is not None else None
                pre_int = nf3.make_fused_pre_3d(
                    param, g.kmax, g.jmax, g.imax, dx, dy, dz, dtype,
                    kl=kl, jl=jl, il=il, ext_pad=H3 - 1, fluid=fl_arg,
                    grid_bands=plan3["int_bands"])[0]
                pre_bnd = nf3.make_fused_pre_3d(
                    param, g.kmax, g.jmax, g.imax, dx, dy, dz, dtype,
                    kl=kl, jl=jl, il=il, ext_pad=H3 - 1, fluid=fl_arg,
                    grid_bands=plan3["bnd_bands"])[0]

            def exchange_buffers(u, v, w):
                return (deep_sched(embed_deep(u, H3)),
                        deep_sched(embed_deep(v, H3)),
                        deep_sched(embed_deep(w, H3)))

            def buffer_maxima(ud, vd, wd):
                return (reduction(jnp.max(jnp.abs(ud)), comm, "max"),
                        reduction(jnp.max(jnp.abs(vd)), comm, "max"),
                        reduction(jnp.max(jnp.abs(wd)), comm, "max"))

            def step_overlap(u, v, w, p, t, nt, ud, vd, wd,
                             um, vm, wm, gen, cap=None):
                pre_k, post_k = fused_k
                pre_i = pre_int if pre_int is not None else pre_k
                pre_b = pre_bnd if pre_bnd is not None else pre_k
                dt = (cfl_from_maxima(um, vm, wm) if adaptive
                      else jnp.asarray(param.dt, dtype))
                dt = _ovl.generation_guard(dt, gen, nt)
                dt = clamped_dt(dt, dt_scale)
                offs = jnp.stack([
                    get_offsets("k", kl), get_offsets("j", jl),
                    get_offsets("i", il),
                ]).astype(jnp.int32)
                dt11 = jnp.full((1, 1), dt, dtype)
                pre_extra = post_extra = ()
                if gmasks is not None:
                    flg_deep, flg_ext = fused_flag_blocks()
                    pre_extra = (flg_deep,)
                    post_extra = (flg_ext,)
                ints = pre_i(offs, dt11, pad_deep(embed_deep(u, H3)),
                             pad_deep(embed_deep(v, H3)),
                             pad_deep(embed_deep(w, H3)), *pre_extra)
                bnds = pre_b(offs, dt11, pad_deep(ud), pad_deep(vd),
                             pad_deep(wd), *pre_extra)
                u, v, w, f, g_, h, rhs = _ovl.merge_halves(
                    int_mask,
                    [strip_deep(unpad_deep(a), H3) for a in ints],
                    [strip_deep(unpad_deep(b), H3) for b in bnds])
                p, _res, _it = (solve(p, rhs, cap) if cap is not None
                                else solve(p, rhs))
                up, vp, wp, um_l, vm_l, wm_l = post_k(
                    offs, dt11, pad_ext(u), pad_ext(v), pad_ext(w),
                    pad_ext(f), pad_ext(g_), pad_ext(h), pad_ext(p),
                    *post_extra,
                )
                u = unpad_ext(up)
                v = unpad_ext(vp)
                w = unpad_ext(wp)
                um = reduction(um_l, comm, "max")
                vm = reduction(vm_l, comm, "max")
                wm = reduction(wm_l, comm, "max")
                # post the next step's exchange into the double buffer
                ud, vd, wd = exchange_buffers(u, v, w)
                t_next = t + dt.astype(idx_dtype)
                if _flags.verbose():
                    master_print(comm, "TIME {} , TIMESTEP {}", t_next, dt)
                capt = (next_cap(_res, _it),) if cap is not None else ()
                return (u, v, w, p, t_next, nt + 1, ud, vd, wd,
                        um, vm, wm, nt + 1, _res, _it, dt) + capt

        step_impl = step if fused_k is None else step_fused
        te = param.te
        chunk = self.CHUNK
        # K-step fused chunks + per-tier exchange depth (ISSUE 17; see
        # models/ns2d_dist.py for the full invariants): K=1 keeps the
        # historical while-body verbatim, K>=2 advances by one scan of
        # K time-gated steps whose body traces once
        kfuse = _dispatch.resolve_chunk_fuse(
            param, "ns3d_dist_chunk_fuse", chunk,
            why_not=("overlapped chunk carries its own cross-step "
                     "exchange pipeline") if overlap else None)
        depth_why = None
        if fused_k is None:
            depth_why = "needs the fused deep-halo step (tpu_fuse_phases)"
        elif self.ragged:
            depth_why = "ragged decomposition"
        elif field_faults:
            depth_why = "PAMPI_FAULTS field faults armed"
        part_names = [n for n in comm.axis_names if comm.axis_size(n) > 1]
        part_ext = [{"k": kl, "j": jl, "i": il}[n] for n in part_names]
        depths = _dispatch.resolve_exchange_depth(
            param, "ns3d_dist_exchange_depth", kfuse, dict(comm.tiers),
            part_names, part_ext,
            FUSE_DEEP_HALO if fused_k is not None else 1,
            why_not=depth_why)
        dax, ddepth = next(iter(depths.items())) if depths else (None, 0)
        self._exchange_depths = depths

        def fuse_block_scan(c, kblock):
            # see models/ns2d_dist.fuse_block_scan
            if dax is None:
                c, _ = lax.scan(kblock(None), c, None, length=kfuse)
                return c
            from ..parallel.comm import capture_axis_strips

            def dblock(c, _):
                s = tuple(
                    capture_axis_strips(x, comm, dax, ddepth,
                                        FUSE_DEEP_HALO)
                    for x in (c[0], c[1], c[2]))
                c, _ = lax.scan(kblock(s), c, None, length=ddepth)
                return c, None

            c, _ = lax.scan(dblock, c, None, length=kfuse // ddepth)
            return c

        def chunk_kernel(u, v, w, p, t, nt):
            def cond(c):
                return jnp.logical_and(c[4] <= te, c[6] < chunk)

            if kfuse > 1:
                def kblock(strips):
                    skw = {} if strips is None else {"strips": strips}

                    def blk(c, _):
                        def live(c):
                            if use_cap:
                                u, v, w, p, t, nt, cap = c
                                return step_impl(u, v, w, p, t, nt, cap,
                                                 **skw)
                            u, v, w, p, t, nt = c
                            return step_impl(u, v, w, p, t, nt, **skw)

                        return lax.cond(c[4] <= te, live,
                                        lambda c: c, c), None

                    return blk

                def body(c):
                    sc = fuse_block_scan(c[:6] + c[7:], kblock)
                    return sc[:6] + (c[6] + kfuse,) + sc[6:]
            else:
                def body(c):
                    if use_cap:
                        u, v, w, p, t, nt, k, cap = c
                        u, v, w, p, t, nt, cap = step_impl(u, v, w, p, t, nt,
                                                           cap)
                        return u, v, w, p, t, nt, k + 1, cap
                    u, v, w, p, t, nt, k = c
                    u, v, w, p, t, nt = step_impl(u, v, w, p, t, nt)
                    return u, v, w, p, t, nt, k + 1

            init = (u, v, w, p, t, nt, jnp.asarray(0, jnp.int32))
            if use_cap:
                init = init + (itermax_i,)
            out = lax.while_loop(cond, body, init)
            return out[0], out[1], out[2], out[3], out[4], out[5]

        def chunk_kernel_metrics(u, v, w, p, t, nt, m):
            # the telemetry twin (see models/ns2d_dist.py)
            def cond(c):
                return jnp.logical_and(c[4] <= te, c[6] < chunk)

            if kfuse > 1:
                def kblock(strips):
                    skw = {} if strips is None else {"strips": strips}

                    def blk(c, _):
                        def live(c):
                            if use_cap:
                                (u, v, w, p, t, nt, res, it, dtv, um,
                                 vm, wm, bad, cap) = c
                                (u, v, w, p, t, nt, res, it, dtv, um,
                                 vm, wm, cap) = step_impl(
                                    u, v, w, p, t, nt, cap, **skw)
                            else:
                                (u, v, w, p, t, nt, res, it, dtv, um,
                                 vm, wm, bad) = c
                                (u, v, w, p, t, nt, res, it, dtv, um,
                                 vm, wm) = step_impl(u, v, w, p, t, nt,
                                                     **skw)
                            # POST-step nt: divergence records name the
                            # true step inside the K-block
                            (res, it, dtv, um, vm, wm,
                             bad) = _tm.metrics_step(
                                bad, nt, res, it, dtv, um, vm, wm)
                            out = (u, v, w, p, t, nt, res, it, dtv, um,
                                   vm, wm, bad)
                            return out + ((cap,) if use_cap else ())

                        return lax.cond(c[4] <= te, live,
                                        lambda c: c, c), None

                    return blk

                def body(c):
                    sc = fuse_block_scan(c[:6] + c[7:], kblock)
                    return sc[:6] + (c[6] + kfuse,) + sc[6:]
            else:
                def body(c):
                    if use_cap:
                        (u, v, w, p, t, nt, k, res, it, dtv, um, vm, wm,
                         bad, cap) = c
                        (u, v, w, p, t, nt, res, it, dtv, um, vm, wm,
                         cap) = step_impl(u, v, w, p, t, nt, cap)
                    else:
                        (u, v, w, p, t, nt, k, res, it, dtv, um, vm, wm,
                         bad) = c
                        (u, v, w, p, t, nt,
                         res, it, dtv, um, vm, wm) = step_impl(u, v, w, p,
                                                               t, nt)
                    res, it, dtv, um, vm, wm, bad = _tm.metrics_step(
                        bad, nt, res, it, dtv, um, vm, wm)
                    out = (u, v, w, p, t, nt, k + 1,
                           res, it, dtv, um, vm, wm, bad)
                    return out + ((cap,) if use_cap else ())

            init = (u, v, w, p, t, nt, jnp.asarray(0, jnp.int32),
                    m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT],
                    m[_tm.M_UMAX], m[_tm.M_VMAX], m[_tm.M_WMAX],
                    m[_tm.M_BAD])
            if use_cap:
                init = init + (itermax_i,)
            out = lax.while_loop(cond, body, init)
            (u, v, w, p, t, nt, _k,
             res, it, dtv, um, vm, wm, bad) = out[:14]
            return u, v, w, p, t, nt, _tm.metrics_pack(
                res, it, dtv, um, vm, wm, bad)

        if overlap:
            # the overlapped chunk (see models/ns2d_dist.py): prologue
            # exchange fills the first double-buffer generation; the
            # internal carry grows (ud, vd, wd, um, vm, wm, gen) while
            # the chunk's EXTERNAL state arity stays unchanged
            def chunk_kernel_overlap(u, v, w, p, t, nt):
                ud, vd, wd = exchange_buffers(u, v, w)
                um, vm, wm = buffer_maxima(ud, vd, wd)

                def cond(c):
                    return jnp.logical_and(c[4] <= te, c[6] < chunk)

                def body(c):
                    if use_cap:
                        (u, v, w, p, t, nt, k, ud, vd, wd, um, vm, wm,
                         gen, cap) = c
                        (u, v, w, p, t, nt, ud, vd, wd, um, vm, wm, gen,
                         _res, _it, _dt, cap) = step_overlap(
                            u, v, w, p, t, nt, ud, vd, wd, um, vm, wm,
                            gen, cap)
                        return (u, v, w, p, t, nt, k + 1, ud, vd, wd,
                                um, vm, wm, gen, cap)
                    u, v, w, p, t, nt, k, ud, vd, wd, um, vm, wm, gen = c
                    (u, v, w, p, t, nt, ud, vd, wd, um, vm, wm, gen,
                     _res, _it, _dt) = step_overlap(
                        u, v, w, p, t, nt, ud, vd, wd, um, vm, wm, gen)
                    return (u, v, w, p, t, nt, k + 1, ud, vd, wd,
                            um, vm, wm, gen)

                init = (u, v, w, p, t, nt, jnp.asarray(0, jnp.int32),
                        ud, vd, wd, um, vm, wm, nt)
                if use_cap:
                    init = init + (itermax_i,)
                out = lax.while_loop(cond, body, init)
                return out[0], out[1], out[2], out[3], out[4], out[5]

            def chunk_kernel_overlap_metrics(u, v, w, p, t, nt, m):
                ud, vd, wd = exchange_buffers(u, v, w)
                um, vm, wm = buffer_maxima(ud, vd, wd)

                def cond(c):
                    return jnp.logical_and(c[4] <= te, c[6] < chunk)

                def body(c):
                    if use_cap:
                        (u, v, w, p, t, nt, k, ud, vd, wd, um, vm, wm,
                         gen, res, it, dtv, mum, mvm, mwm, bad, cap) = c
                        (u, v, w, p, t, nt, ud, vd, wd, um, vm, wm, gen,
                         res, it, dtv, cap) = step_overlap(
                            u, v, w, p, t, nt, ud, vd, wd, um, vm, wm,
                            gen, cap)
                    else:
                        (u, v, w, p, t, nt, k, ud, vd, wd, um, vm, wm,
                         gen, res, it, dtv, mum, mvm, mwm, bad) = c
                        (u, v, w, p, t, nt, ud, vd, wd, um, vm, wm, gen,
                         res, it, dtv) = step_overlap(
                            u, v, w, p, t, nt, ud, vd, wd, um, vm, wm,
                            gen)
                    res, it, dtv, mum, mvm, mwm, bad = _tm.metrics_step(
                        bad, nt, res, it, dtv, um, vm, wm)
                    out = (u, v, w, p, t, nt, k + 1, ud, vd, wd,
                           um, vm, wm, gen,
                           res, it, dtv, mum, mvm, mwm, bad)
                    return out + ((cap,) if use_cap else ())

                init = (u, v, w, p, t, nt, jnp.asarray(0, jnp.int32),
                        ud, vd, wd, um, vm, wm, nt,
                        m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT],
                        m[_tm.M_UMAX], m[_tm.M_VMAX], m[_tm.M_WMAX],
                        m[_tm.M_BAD])
                if use_cap:
                    init = init + (itermax_i,)
                out = lax.while_loop(cond, body, init)
                (u, v, w, p, t, nt, _k, _ud, _vd, _wd, _um, _vm, _wm,
                 _gen, res, it, dtv, mum, mvm, mwm, bad) = out[:21]
                return u, v, w, p, t, nt, _tm.metrics_pack(
                    res, it, dtv, mum, mvm, mwm, bad)

        def init_kernel():
            shape = (kl + 2, jl + 2, il + 2)
            return (
                jnp.full(shape, param.u_init, dtype),
                jnp.full(shape, param.v_init, dtype),
                jnp.full(shape, param.w_init, dtype),
                jnp.full(shape, param.p_init, dtype),
            )

        def collect_kernel(u, v, w, p):
            """Cell-centered interiors (≙ commCollectResult, comm.c:246-427):
            staggered→center averaging needs fresh minus-side halos."""
            u = halo_exchange(u, comm)
            v = halo_exchange(v, comm)
            w = halo_exchange(w, comm)
            pg = p[1:-1, 1:-1, 1:-1]
            ug = (u[1:-1, 1:-1, 1:-1] + u[1:-1, 1:-1, :-2]) / 2.0
            vg = (v[1:-1, 1:-1, 1:-1] + v[1:-1, :-2, 1:-1]) / 2.0
            wg = (w[1:-1, 1:-1, 1:-1] + w[:-2, 1:-1, 1:-1]) / 2.0
            return ug, vg, wg, pg

        spec = P("k", "j", "i")
        self._init_sm = jax.jit(
            comm.shard_map(init_kernel, in_specs=(), out_specs=(spec,) * 4)
        )
        mextra = (P(),) if metrics else ()
        if overlap:
            chunk_fn = (chunk_kernel_overlap_metrics if metrics
                        else chunk_kernel_overlap)
        else:
            chunk_fn = chunk_kernel_metrics if metrics else chunk_kernel
        self._chunk_sm = jax.jit(
            comm.shard_map(
                chunk_fn,
                in_specs=(spec,) * 4 + (P(), P()) + mextra,
                out_specs=(spec,) * 4 + (P(), P()) + mextra,
                check_vma=not pallas_o,
            )
        )
        self._collect_sm = jax.jit(
            comm.shard_map(collect_kernel, in_specs=(spec,) * 4, out_specs=(spec,) * 4)
        )
        _tm.emit("build", family="ns3d_dist",
                 grid=[g.kmax, g.jmax, g.imax], mesh=list(comm.dims),
                 trace_wall_s=round(time.perf_counter() - self._t0_build, 3),
                 phases=_dispatch.last("ns3d_dist_phases"))
        # static per-shard halo-exchange byte counts (step-level
        # exchanges of the dispatched path; solve internals excluded).
        # Built unconditionally: the telemetry `halo` record and the
        # commcheck trace census read the SAME dict, both priced by
        # comm.halo_exchange_bytes (see models/ns2d_dist._halo_record).
        isz = jnp.dtype(dtype).itemsize
        rec = {
            "family": "ns3d_dist", "mesh": list(comm.dims),
            "shard": [kl, jl, il], "dtype": str(jnp.dtype(dtype)),
            "path": "fused" if fused_k is not None else "jnp",
            "exchange_bytes_depth1":
                halo_exchange_bytes((kl, jl, il), 1, isz),
        }
        if fused_k is not None:
            from ..ops.ns3d_fused import fused_deep_layout_3d

            fbk, _fh3, fpw, fnb3 = fused_deep_layout_3d(
                kl, jl, il, dtype, FUSE_DEEP_HALO - 1,
                masked=gmasks is not None)
            full_cells = fnb3 * fbk * fpw
            rec.update(
                deep_halo=FUSE_DEEP_HALO,
                deep_exchange_bytes=halo_exchange_bytes(
                    (kl, jl, il), FUSE_DEEP_HALO, isz),
                exchanges_per_step={"deep": 3},
                pre_grid_cells=full_cells,
            )
            if self._exchange_depths:
                # per-tier depth map (ISSUE 17; see models/ns2d_dist.py):
                # the mapped dcn axis captures once per block, the
                # per-step deep strips then cover the unmapped axes only
                rec.update(
                    exchange_depths=dict(self._exchange_depths),
                    depth_block=max(self._exchange_depths.values()),
                    exchanges_per_block={"deep": 3},
                    axes=list(comm.axis_names),
                )
            if overlap:
                # same per-step schedule, posted into the double buffer;
                # the chunk prologue fills the first generation (see
                # models/ns2d_dist.py)
                rec.update(path="fused_overlap",
                           overlap="double_buffered",
                           exchanges_per_chunk={"deep": 3},
                           pre_grid_cells=(
                               self._overlap_plan["cells"]
                               if self._overlap_plan is not None
                               else 2 * full_cells),
                           pre_grid_cells_full=2 * full_cells)
        else:
            rec.update(exchanges_per_step={
                "depth1": 6 + (3 if gmasks is not None else 0),
                "shift": 3,
            })
        # hierarchical-exchange accounting (ROADMAP item 3): the axis->
        # tier map and the per-step DCN-tier bytes — 0 on single-tier
        # meshes, the first-class slow-fabric BENCH metric on a
        # multi-slice pod (tools/bench_trend.py gates it downward)
        from ..parallel.comm import exchange_schedule_tier_bytes

        rec["tier_map"] = dict(comm.tiers)
        rec["dcn_exchange_bytes"] = exchange_schedule_tier_bytes(
            comm, rec).get("dcn", 0)
        self._halo_rec = rec
        if _tm.enabled():
            _tm.emit("halo", **rec)

    # ------------------------------------------------------------------
    def _halo_record(self) -> dict:
        """The static halo-exchange accounting of the dispatched path —
        see models/ns2d_dist._halo_record (the commcheck cross-check
        hook)."""
        return dict(self._halo_rec)

    def _rebuild_chunk(self):
        """Rebuild every traced kernel against the solver's CURRENT
        attributes (recovery dt clamp) — the rollback-recovery rebuild hook
        (models/_driver.RingRecovery). Advances the fault-injection
        generation (see models/ns2d._rebuild_chunk)."""
        self._field_faults = _fi.take_field_faults()
        self._build()
        return self._chunk_sm

    def initial_state(self) -> tuple:
        """(u, v, w, p, t, nt[, metrics]) matching the built chunk's arity
        (the NS-2D convention — see models/ns2d.initial_state)."""
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        state = (self.u, self.v, self.w, self.p,
                 jnp.asarray(self.t, time_dtype),
                 jnp.asarray(self.nt, jnp.int32))
        if self._metrics:
            state = state + (_tm.metrics_init(),)
        return state

    def run(self, progress: bool = True, on_sync=None) -> None:
        """The shared drive loop (models/_driver.drive_chunks) — see
        models/ns2d_dist.run for the migration contract."""
        from ._driver import coord_ckpt_cadence, drive_chunks, make_recovery

        bar = Progress(self.param.te, enabled=progress and not _flags.verbose())
        state = self.initial_state()
        rec = (_tm.ChunkRecorder("ns3d_dist", self.nt)
               if self._metrics else None)
        recover = make_recovery(self, "ns3d_dist", time_index=4,
                                recorder=rec)

        def publish(s):
            self.u, self.v, self.w, self.p = s[0], s[1], s[2], s[3]
            self.t, self.nt = float(s[4]), int(s[5])

        def on_state(s):
            if rec is not None:
                rec.update(float(s[4]), int(s[5]), s[6])
            if recover is not None:
                recover.capture(s)
            if on_sync is not None:
                publish(s)
                on_sync(self)

        if recover is not None:
            recover.capture(state)  # first-chunk divergence is recoverable
        # multi-process transient retry rides the agreement protocol —
        # see models/ns2d_dist.run for the lifted single-controller ban
        from ..parallel.coordinator import make_coordinator

        coord = make_coordinator(self.param, "ns3d_dist")
        budget = 1 if (coord is not None or jax.process_count() == 1) else 0
        ckpt_every, on_ckpt = coord_ckpt_cadence(self, coord, publish)
        nt0 = self.nt
        with _xprof.capture("ns3d_dist", steps=lambda: self.nt - nt0):
            state = drive_chunks(
                state, self._chunk_sm, self.param.te, 4, bar,
                retry=lambda: None, on_state=on_state,
                replenish_after=self.param.tpu_retry_replenish,
                recover=recover, transient_budget=budget,
                coordinator=coord, ckpt_every=ckpt_every,
                on_ckpt=on_ckpt, family="ns3d_dist",
                ledger=getattr(self, "_fault_ledger", None))
            publish(state)
        self._emit_exchange_span()

    def _emit_exchange_span(self) -> None:
        """The `exchange` span — see models/ns2d_dist._emit_exchange_span
        (the serial critical-path probe of the declared halo schedule)."""
        if not _tm.enabled():
            return
        from ..parallel.comm import exchange_schedule_bytes, time_exchange_ms

        rec = self._halo_record()
        _tm.emit_span(
            f"{rec['family']}.exchange",
            time_exchange_ms(self.comm, rec),
            path=rec["path"], mesh=rec["mesh"], shard=rec["shard"],
            bytes_per_step=exchange_schedule_bytes(rec),
            mode="serial_probe")

    def collect(self):
        """Gather cell-centered global fields to the host. The collect
        kernel outputs interior-only blocks, so the shard_map output IS the
        assembled (kmax, jmax, imax) global array — no assembly code (the
        80-line subarray dance of assembleResult, comm.c:104-156, vanishes)."""
        ug, vg, wg, pg = self._collect_sm(self.u, self.v, self.w, self.p)
        fetch = self.comm.collect  # multihost-safe host gather
        out = (fetch(ug), fetch(vg), fetch(wg), fetch(pg))
        g = self.grid
        # ragged decompositions carry trailing dead cells — strip them
        return tuple(a[: g.kmax, : g.jmax, : g.imax] for a in out)

    # -- elastic-checkpoint contract (utils/checkpoint.save_elastic) ---
    def global_shape(self) -> tuple:
        g = self.grid
        return (g.kmax + 2, g.jmax + 2, g.imax + 2)

    def global_fields(self) -> dict:
        """Mesh-independent reference-layout globals — see
        models/ns2d_dist.global_fields (same helper, 3-D mesh)."""
        from ..utils.checkpoint import assemble_global

        g = self.grid
        return {
            f: assemble_global(
                self.comm.collect(getattr(self, f)), self.comm.dims,
                (self.kl, self.jl, self.il), (g.kmax, g.jmax, g.imax))
            for f in ("u", "v", "w", "p")
        }

    def set_global_fields(self, fields: dict) -> None:
        from ..utils.checkpoint import scatter_blocks

        for f, arr in fields.items():
            cur = getattr(self, f)
            stacked = scatter_blocks(
                np.asarray(arr), self.comm.dims,
                (self.kl, self.jl, self.il))
            new = jnp.asarray(stacked, cur.dtype)
            sh = getattr(cur, "sharding", None)
            if sh is not None:
                new = jax.device_put(new, sh)
            setattr(self, f, new)

    def write_result(self, path=None, fmt: str = "ascii") -> None:
        # collect() is collective; only rank 0 writes the serial VTK file
        fields = self.collect()
        if self.comm.is_master:
            write_vtk_result(self.param, self.grid, fields, path, fmt)

    def write_result_sharded(self, path=None) -> None:
        """MPI-IO-pattern parallel write (binary VTK): the collect kernel's
        output is a mesh-sharded global array, and every addressable shard's
        slab goes straight to its byte offsets in the shared file — no global
        gather to the host (≙ the reference's scaffolded MPI_File_set_view
        path, vtkWriter.c:118-143, completed)."""
        from ..utils.vtkio import ShardedVtkWriter, shards_of

        if self.ragged:
            # per-shard slabs would carry dead cells at wrong file offsets;
            # the gathered serial write strips them instead
            self.write_result(path=path, fmt="binary")
            return
        ug, vg, wg, pg = self._collect_sm(self.u, self.v, self.w, self.p)
        problem = self.param.name.replace("3d", "")  # same naming as serial
        writer = ShardedVtkWriter(problem, self.grid, path=path)
        writer.scalar("pressure", shards_of(pg))
        us, vs, ws = shards_of(ug), shards_of(vg), shards_of(wg)
        vec = []
        for (du, o1), (dv, o2), (dw, o3) in zip(us, vs, ws):
            assert o1 == o2 == o3, "component shard layouts diverged"
            vec.append((du, dv, dw, o1))
        writer.vector("velocity", vec)
        writer.close()
