"""Shared chunked time-loop driver for the NS solvers.

Both NS-2D and NS-3D advance a carried state tuple through jitted chunk
calls (CHUNK device steps per host sync) with the same runtime-retry
protocol: a shape-specific pallas failure the dispatcher probe missed
rebuilds the chunk on the jnp path (same arithmetic) and retries the chunk —
inputs are unchanged because the loop is functional. This module is that
protocol's single home; the solvers supply the state arity and rebuild hook.
"""

from __future__ import annotations

import jax


def _is_transient_device_fault(exc) -> bool:
    """The axon-tunnelled chip intermittently raises UNAVAILABLE device
    errors on large programs that run fine on the next dispatch (measured:
    the same jitted solve failing then succeeding 3x in a row). Those are
    worth exactly one same-chunk retry; anything else is a real error."""
    return type(exc).__name__ == "JaxRuntimeError" and "UNAVAILABLE" in str(exc)


def drive_chunks(state, chunk_fn, te, time_index, bar, retry, on_state=None):
    """Run `state = chunk_fn(*state)` while state[time_index] <= te
    (main.c:43-60 loop semantics: a step runs whenever t <= te at its start).

    retry() is called when a chunk raises: it returns a rebuilt chunk_fn to
    retry with, or None if there is no alternative path (the failure was not
    pallas's). In the None case a TRANSIENT device fault still gets one
    same-chunk retry (inputs are unchanged — the loop is functional) before
    re-raising. on_state(state) fires after every successful chunk — the
    host-sync / checkpoint hook point. Returns the final state."""
    transient_budget = 1
    while float(state[time_index]) <= te:
        try:
            new = chunk_fn(*state)
            # force completion: async pallas faults surface here
            float(new[time_index])
        except Exception as exc:
            new_fn = retry()
            if new_fn is None:
                if transient_budget > 0 and _is_transient_device_fault(exc):
                    import warnings

                    warnings.warn(
                        "transient TPU device fault; retrying the chunk once",
                        stacklevel=2,
                    )
                    transient_budget -= 1
                    continue
                raise
            chunk_fn = new_fn
            continue
        state = new
        bar.update(float(state[time_index]))
        if on_state is not None:
            on_state(state)
    bar.stop()
    return state


def pallas_retry(solver, what: str):
    """The retry() hook for a solver with `_backend`/`_uses_pallas`/
    `_build_chunk`/`_chunk_fn`: falls back to the jnp chunk exactly once; a
    failure on the jnp path (or with pallas not even in play) re-raises."""

    def retry():
        if solver._backend == "jnp" or not solver._uses_pallas():
            return None  # the failing chunk never ran pallas — genuine error
        import warnings

        warnings.warn(
            f"pallas {what} failed at runtime; retrying this chunk on the "
            "jnp path", stacklevel=2,
        )
        solver._backend = "jnp"
        solver._chunk_fn = jax.jit(solver._build_chunk(backend="jnp"))
        return solver._chunk_fn

    return retry
