"""Shared chunked time-loop driver for the NS solvers.

All four NS families advance a carried state tuple through jitted chunk
calls (CHUNK device steps per host sync) with the same failure-handling
protocol, and this module is that protocol's single home — the solvers
supply the state arity, the rebuild hook, and the ring-capture callback:

- pallas runtime failure: a shape-specific fault the dispatcher probe
  missed rebuilds the chunk on the jnp path (same arithmetic) and retries
  the chunk — inputs are unchanged because the loop is functional. After
  `restore_after` consecutive clean chunks on the fallback, the pallas
  chunk is rebuilt and restored (a 10-hour run should not pay jnp speed
  forever for one transient kernel fault); a pallas that breaks again
  right after a restore is treated as deterministically broken and stays
  on jnp.
- transient `UNAVAILABLE` device fault: one same-chunk retry, with a
  budget that REFILLS after `replenish_after` consecutive clean chunks
  (PR 4; previously one per run — satellite fix).
- divergence: a NaN loop time is terminal for the loop, but when a
  `RingRecovery` is armed (tpu_recover_ring > 0) the loop rolls back to
  the last captured finite state and re-drives with a clamped dt instead
  of terminating.

Every consumption emits a structured telemetry record (`retry` /
`recover`); the injection plane (`utils/faultinject.py`, PAMPI_FAULTS)
forges each fault class deterministically so tests prove the protocol
end-to-end.
"""

from __future__ import annotations

import math
import warnings
from collections import deque

import jax

from ..utils import faultinject as _fi
from ..utils import telemetry as _tm


def _is_transient_device_fault(exc) -> bool:
    """The axon-tunnelled chip intermittently raises UNAVAILABLE device
    errors on large programs that run fine on the next dispatch (measured:
    the same jitted solve failing then succeeding 3x in a row). Those are
    worth a same-chunk retry; anything else is a real error."""
    return type(exc).__name__ == "JaxRuntimeError" and "UNAVAILABLE" in str(exc)


def clamped_dt(dt, scale):
    """Trace-time dt clamp for rollback-recovery rebuilds: every family's
    step multiplies its computed (or constant) dt through here. Identity —
    the SAME tracer, zero added ops — at the default scale 1.0, so the
    uninjected/unrecovered trace is byte-identical."""
    if scale == 1.0:
        return dt
    import jax.numpy as jnp

    return dt * jnp.asarray(scale, dt.dtype)


def drive_chunks(state, chunk_fn, te, time_index, bar, retry, on_state=None,
                 lookahead: int = 0, replenish_after: int = 8, recover=None,
                 transient_budget: int = 1, coordinator=None,
                 ckpt_every: int = 0, on_ckpt=None, family: str = "",
                 ledger=None):
    """Run `state = chunk_fn(*state)` while state[time_index] <= te
    (main.c:43-60 loop semantics: a step runs whenever t <= te at its start).

    retry() is called when a chunk raises: it returns a rebuilt chunk_fn to
    retry with, or None if there is no alternative path (the failure was not
    pallas's). In the None case a TRANSIENT device fault still gets one
    same-chunk retry (inputs are unchanged — the loop is functional) before
    re-raising; the transient budget refills after `replenish_after`
    consecutive clean chunks (0 = never — the historical one-per-run
    budget); `transient_budget=0` disables the transient retry entirely
    (the multi-process dist case: a rank-local re-dispatch would
    desynchronize collectives across ranks — let the error kill the job
    cleanly instead). If retry has an `on_clean_chunk()` hook
    (pallas_retry), it is
    consulted after every confirmed chunk and may hand back a restored
    pallas chunk_fn. on_state(state) fires after every successful chunk —
    the host-sync / checkpoint / ring-capture hook point. Returns the final
    state (the first whose time exceeds te).

    recover, when not None, is a RingRecovery: a confirmed NaN loop time
    (adaptive-dt blow-up) OR a fired in-band divergence sentinel (field-only
    blow-up under telemetry) triggers recover.attempt() — roll back to the
    last captured finite state, clamp dt, re-drive — instead of returning
    the diverged state; the loop only lands on the diverged state
    terminally once the recovery gives up (attempts exhausted / nothing to
    roll back to).

    lookahead > 0 pipelines the dispatch: up to lookahead+1 chunks stay in
    flight (the one being confirmed plus `lookahead` queued behind it — so
    lookahead=0 is one in flight, the serial case) and the host reads the
    loop time only from the OLDEST of them,
    so the per-chunk host<->device round trip (the dominant end-to-end cost
    under a high-latency tunnel — measured 27.7 vs the chip's 12.7 ms/step
    at dcavity 4096^2) overlaps the younger chunks' device execution. Safe
    by construction: a chunk dispatched past te is a device no-op (its own
    while-cond sees t > te and passes the state through), so speculative
    overshoot never advances the simulation, and the (undonated) input
    buffers stay alive for the retry path. On any failure the pipeline
    resets to the last CONFIRMED state — the retry protocol is unchanged,
    it just may re-dispatch the speculative tail. lookahead=0 is exactly
    the historical dispatch-then-sync loop.

    coordinator, when not None, routes the whole loop through the
    chunk-boundary agreement protocol (parallel/coordinator.py): ranks
    allgather a small fault word at each boundary and take every
    retry / rollback / checkpoint decision identically — the seam that
    lifts the multi-process `transient_budget=0` ban. None (the
    single-process default) is THIS exact loop, untouched. The
    coordinated path forces lookahead=0 (every boundary is a
    rendezvous) and takes the agreed checkpoint cadence from
    `ckpt_every`/`on_ckpt` instead of an on_state counter.

    ledger, when not None, is a restored FAULT LEDGER (the elastic
    manifest's `ledger` key, stashed on the solver by
    utils/checkpoint.load_elastic): the spent transient budget carries
    over so a resumed run starts with the charge it died with — the
    rank-symmetric no-amnesia contract (the pallas verdict and dt clamp
    were already re-applied at load time)."""
    if coordinator is not None:
        from ..parallel.coordinator import drive_coordinated

        return drive_coordinated(
            state, chunk_fn, te, time_index, bar, retry,
            coordinator, on_state=on_state,
            replenish_after=replenish_after, recover=recover,
            transient_budget=transient_budget, ckpt_every=ckpt_every,
            on_ckpt=on_ckpt, family=family, ledger=ledger,
        )
    if lookahead < 0:
        # cli.py validates the .par key; programmatic callers land here (a
        # negative value would popleft an empty deque and surface an
        # IndexError through the device-fault retry path)
        raise ValueError(f"lookahead must be >= 0 (got {lookahead})")
    max_transient = max(0, transient_budget)  # replenish refills to THIS
    if ledger:
        # resumed run: start with the spent charge, refill to the full
        # budget on the usual clean streak
        transient_budget = max(
            0, transient_budget - int(ledger.get("budget_spent", 0)))
    clean = 0  # consecutive confirmed chunks since the last fault/recovery
    # per-chunk steps/s + ETA line behind PAMPI_PROFILE (utils/progress.
    # ChunkEta): a multi-minute run stops being a silent decile bar. The
    # state convention (..., t, nt[, metrics]) puts nt right after the
    # loop time (the make_recovery contract), so the line costs one tiny
    # scalar readback per chunk — and only when the flag is armed, on
    # process 0 only (the master-only emitter convention; N ranks
    # \r-redrawing one terminal would garble it).
    from ..utils import profiling as _prof
    from ..utils.progress import ChunkEta

    eta = (ChunkEta(te)
           if _prof.enabled() and jax.process_index() == 0 else None)
    if eta is not None and hasattr(bar, "disable"):
        bar.disable()  # one \r-redrawn line at a time — the ETA wins
    if float(state[time_index]) > te:
        bar.stop()
        return state

    pending = deque()  # in-flight states, oldest first
    confirmed = state  # last state whose time read succeeded
    newest = state
    final = None
    while final is None:
        try:
            if len(pending) <= lookahead:
                _fi.maybe_chunk_fault()  # injected fault plane (test-only)
                newest = chunk_fn(*newest)
                pending.append(newest)
                continue
            old = pending.popleft()
            # force completion of the oldest in-flight chunk: async pallas
            # faults surface here, overlapped with the younger dispatches
            t_old = float(old[time_index])
        except Exception as exc:  # lint: allow(broad-except) — the fault-classification funnel: every runtime error class routes to transient/pallas/raise below
            if isinstance(exc, _fi.FaultSpecError):
                raise  # a broken TEST spec fails loudly at the first hook
                # — never classified as a kernel fault or retried
            pending.clear()
            newest = confirmed
            clean = 0
            if _is_transient_device_fault(exc):
                # handled BEFORE (and never by) the pallas hook: a
                # transient UNAVAILABLE is a device hiccup, not a kernel
                # fault — it gets the same-chunk retry while the budget
                # lasts and RE-RAISES once exhausted. Routing it into the
                # pallas fallback would misclassify the fault and, after a
                # restore, trip _PallasRetry's deterministically-broken
                # latch on a healthy kernel.
                if transient_budget <= 0:
                    raise
                reset_clean = getattr(retry, "reset_clean", None)
                if reset_clean is not None:
                    reset_clean()  # the fault breaks the clean streak
                warnings.warn(
                    "transient TPU device fault; retrying the chunk once",
                    stacklevel=2,
                )
                transient_budget -= 1
                _tm.emit("retry", fault="transient",
                         budget_left=transient_budget,
                         t=float(confirmed[time_index]))
                continue
            # NOT reset_clean() first: retry() judges the post-restore
            # probation on the streak AS IT STOOD when the fault hit (it
            # zeroes its own counter on fallback) — resetting here would
            # make every post-restore failure look immediate and latch the
            # deterministically-broken verdict on a healthy kernel
            new_fn = retry()
            if new_fn is None:
                raise
            chunk_fn = new_fn
            continue
        confirmed = old
        # a diverged chunk is NOT clean: judge it before the replenish /
        # restore accounting so a poisoned confirmation can neither refill
        # the transient budget nor trigger a pallas restore
        diverged = t_old != t_old or (
            recover is not None and recover.poisoned(old)
        )
        if not diverged:
            clean += 1
            if (replenish_after > 0 and clean >= replenish_after
                    and transient_budget < max_transient):
                transient_budget = max_transient  # M clean chunks: refill
            restore = getattr(retry, "on_clean_chunk", None)
            if restore is not None:
                restored_fn = restore()
                if restored_fn is not None:
                    # in-flight jnp states stay valid — only future
                    # dispatches run the restored pallas chunk
                    chunk_fn = restored_fn
        bar.update(t_old)
        if eta is not None:
            eta.update(t_old, int(old[time_index + 1]))
        if on_state is not None:
            on_state(old)
        # NaN loop time is terminal, not "not yet past te": an adaptive-dt
        # blow-up makes dt and then t NaN, every subsequent chunk is a
        # device no-op (its while-cond sees NaN <= te false), and
        # `t_old > te` is false for NaN — without this the loop would spin
        # forever on no-op dispatches (the dist solvers' old `while t <= te`
        # behaved the same way). The telemetry sentinel, when enabled, has
        # already named the last-good step by the time we land here — and
        # an armed RingRecovery turns the termination into a rollback.
        # NaN t alone MISSES field-only blow-ups (cfl_dt's `where(umax > 0,
        # dx/umax, inf)` selects the finite branch on a NaN maximum, and
        # fixed-dt runs never touch t at all), so an armed recovery also
        # treats a fired in-band sentinel as divergence — the "nothing acts
        # on the sentinel" gap this layer exists to close.
        if diverged or t_old > te:
            if diverged and recover is not None:
                rolled = recover.attempt()
                if rolled is not None:
                    state_rb, new_fn = rolled
                    pending.clear()
                    confirmed = newest = state_rb
                    chunk_fn = new_fn
                    clean = 0
                    reset_clean = getattr(retry, "reset_clean", None)
                    if reset_clean is not None:
                        reset_clean()
                    continue
            # recovery off / gave up: terminate ON the diverged state (a
            # diagnostic-bearing early stop, never a hang on garbage)
            final = old
    if eta is not None:
        eta.stop()
    bar.stop()
    return final


class _PallasRetry:
    """The retry() hook for a solver with `_backend`/`_uses_pallas`/
    `_build_chunk`/`_chunk_fn`: falls back to the jnp chunk (same
    arithmetic) when the failing chunk contained a pallas kernel; a failure
    on the jnp path (or with pallas not even in play) returns None so the
    error propagates. Covers the FUSED step-phase chunk too: `_uses_pallas`
    reports the fused kernels, and `_build_chunk(backend="jnp")` both
    selects the jnp solve AND stands the fused phases down
    (resolve_fuse_phases' backend contract), so one fallback recovers from
    a failure in either kernel family.

    restore_after > 0 replenishes the budget: after that many consecutive
    clean chunks on the jnp fallback, the pallas chunk is rebuilt and
    restored (`on_clean_chunk`, called by drive_chunks per clean
    confirmation). A pallas that fails again before the next
    `restore_after` clean chunks is deterministically broken — no further
    restores, the run stays jnp. drive_chunks filters transient
    UNAVAILABLE faults BEFORE this hook, so the broken-latch only ever
    judges genuine kernel failures.

    The jnp rebuild deliberately does NOT advance the field-fault
    injection generation: the failing chunk's armed corruption (if any)
    stays baked, so a combined `pallas@chunkN,nan@stepM:f` spec cannot
    silently run uninjected (solvers consume generations in __init__ and
    `_rebuild_chunk` only)."""

    def __init__(self, solver, what: str, restore_after: int = 0):
        self.solver = solver
        self.what = what
        self.restore_after = restore_after
        self._orig_backend = solver._backend
        self._on_jnp = False   # currently running the fallback chunk
        self._restored = False  # current pallas period came from a restore
        self._dead = False     # pallas judged deterministically broken
        self._clean = 0        # clean chunks since the last transition
        # a restored fault ledger (utils/checkpoint._restore_ledger has
        # already parked the solver on jnp): the deterministically-broken
        # verdict survives the restart — no probation amnesia
        led = (getattr(solver, "_fault_ledger", None) or {}).get("pallas")
        if led and led.get("broken"):
            self._dead = True
            self._on_jnp = solver._backend == "jnp"

    def ledger(self) -> dict:
        """This hook's slice of the coordinator fault ledger
        (parallel/coordinator.CoordinatedLoop.ledger)."""
        return {"broken": bool(self._dead),
                "on_jnp": bool(self._on_jnp),
                "backend": self.solver._backend}

    def __call__(self):
        s = self.solver
        if s._backend == "jnp" or not s._uses_pallas():
            return None  # the failing chunk never ran pallas — genuine error
        if self._restored and self._clean < self.restore_after:
            self._dead = True  # broke again right after a restore
        warnings.warn(
            f"pallas {self.what} failed at runtime; retrying this chunk on "
            "the jnp path", stacklevel=2,
        )
        _tm.emit("retry", fault="pallas", action="jnp_fallback",
                 what=self.what)
        s._backend = "jnp"
        s._chunk_fn = jax.jit(s._build_chunk(backend="jnp"))
        self._on_jnp = True
        self._clean = 0
        return s._chunk_fn

    def on_clean_chunk(self):
        """Per confirmed chunk: once `restore_after` consecutive clean
        chunks ran on the jnp fallback, rebuild and return the pallas
        chunk; None otherwise."""
        self._clean += 1
        if (not self._on_jnp or self._dead or self.restore_after <= 0
                or self._clean < self.restore_after):
            return None
        warnings.warn(
            f"restoring the pallas {self.what} after {self._clean} clean "
            "chunks on the jnp fallback", stacklevel=2,
        )
        _tm.emit("retry", fault="pallas", action="pallas_restore",
                 what=self.what, clean_chunks=self._clean)
        s = self.solver
        s._backend = self._orig_backend
        s._chunk_fn = jax.jit(s._build_chunk(backend=self._orig_backend))
        self._on_jnp = False
        self._restored = True
        self._clean = 0
        return s._chunk_fn

    def reset_clean(self) -> None:
        """Any fault or rollback breaks the consecutive-clean streak
        (drive_chunks calls this alongside its own `clean = 0`)."""
        self._clean = 0


def pallas_retry(solver, what: str, restore_after: int = 0):
    """Build the pallas runtime-retry hook (see _PallasRetry)."""
    return _PallasRetry(solver, what, restore_after=restore_after)


class RingRecovery:
    """Divergence rollback-recovery: an in-memory ring of the last-K
    confirmed finite chunk states (the HOT tier — device-resident
    references, no disk round-trip on the capture path) over the on-disk
    `tpu_checkpoint` as the COLD tier. `capture(state)` is the solvers'
    on_state hook: it keeps a state only when its loop time is finite and
    (with telemetry armed) the in-band sentinel has not fired inside its
    chunk — the ring never holds a poisoned state. `attempt()` is called
    by drive_chunks when the loop confirms divergence (NaN loop time, or a
    fired sentinel when telemetry rides the chunk): pop the newest
    captured state (successive attempts dig progressively deeper — fields
    can rot before t goes NaN), clamp the solver's dt by `dt_scale`
    (cumulative), re-trace the chunk via the solver's `_rebuild_chunk`
    hook, and re-drive. Bounded by `max_attempts` per run; every attempt
    emits a structured `recover` telemetry record, and giving up returns
    the loop to the historical terminate-on-NaN path (a diagnostic, never
    a hang)."""

    def __init__(self, solver, family: str, time_index: int, ring: int = 4,
                 dt_scale: float = 0.5, max_attempts: int = 3,
                 metrics_index=None, recorder=None, ckpt_path: str = ""):
        self.solver = solver
        self.family = family
        self.time_index = time_index
        self.dt_scale = dt_scale
        self.max_attempts = max_attempts
        self.metrics_index = metrics_index
        self.recorder = recorder
        self.ckpt_path = ckpt_path
        self._ring = deque(maxlen=max(1, int(ring)))
        self._attempts = 0
        self._memo_state = None  # last state judged by poisoned()
        self._memo_bad = False

    def capture(self, state) -> None:
        if not math.isfinite(float(state[self.time_index])):
            return
        if self.poisoned(state):
            return  # sentinel fired inside this chunk: poisoned state
        self._ring.append(state)

    def poisoned(self, state) -> bool:
        """The in-band sentinel fired inside this confirmed chunk: fields
        went non-finite even though the loop time is still finite (fixed-dt
        blow-up, NaN velocity maxima taking cfl_dt's finite branch) — the
        divergence the NaN-t trigger alone misses. False when telemetry is
        off (no sentinel rides the chunk). The verdict is memoized per
        state object (identity, with a strong ref): the drive loop and
        capture() both judge every confirmed chunk, and the metrics
        readback should cost one device sync, not two."""
        if self.metrics_index is None:
            return False
        if self._memo_state is state:
            return self._memo_bad
        import numpy as np

        bad = float(np.asarray(state[self.metrics_index])[_tm.M_BAD]) >= 0
        self._memo_state, self._memo_bad = state, bad
        return bad

    def _cold_state(self):
        """Ring exhausted: restore the newest on-disk generation (which
        itself falls back to `.prev` on corruption) and rebuild the chunk
        state at the current arity via initial_state()."""
        if not self.ckpt_path:
            return None
        from ..utils import checkpoint as ckpt

        try:
            # load_any: the cold tier must read whichever format the
            # run's tpu_checkpoint writes (legacy .npz OR the elastic
            # manifest — tpu_ckpt_elastic routes saves, so the sniff
            # keeps rollback working under both)
            ckpt.load_any(self.ckpt_path, self.solver)
        except Exception as exc:  # lint: allow(broad-except) — a cold-tier restore failure of ANY class degrades to "no checkpoint", never kills recovery
            warnings.warn(
                f"{self.family}: cold-tier restore from "
                f"{self.ckpt_path!r} failed ({exc})", stacklevel=2,
            )
            return None
        if not math.isfinite(self.solver.t):
            # belt over save_checkpoint's non-finite refusal: re-driving
            # from a diverged checkpoint would re-diverge instantly and
            # burn every remaining attempt on the same garbage
            warnings.warn(
                f"{self.family}: cold-tier checkpoint {self.ckpt_path!r} "
                "holds a non-finite state; not rolling back to it",
                stacklevel=2,
            )
            return None
        return self.solver.initial_state()

    def newest_nt(self) -> int:
        """Step count of the newest ring-captured state, -1 when empty —
        the rollback generation this rank PROPOSES in the coordinator
        fault word (parallel/coordinator.py; the merged min is what every
        rank then rolls to)."""
        if not self._ring:
            return -1
        return int(self._ring[-1][self.time_index + 1])

    def attempt(self, target_nt=None):
        """Returns (rollback_state, rebuilt_chunk_fn), or None to let the
        loop terminate on the diverged state. `target_nt`, when given (the
        coordinator's AGREED generation), first discards ring entries
        newer than it, so every rank restores the same step count — the
        rank-symmetric rollback contract."""
        self._attempts += 1
        if target_nt is not None:
            while (self._ring
                   and int(self._ring[-1][self.time_index + 1]) > target_nt):
                self._ring.pop()
        if self._attempts > self.max_attempts:
            _tm.emit("recover", family=self.family, attempt=self._attempts,
                     gave_up=True, reason="max_attempts")
            warnings.warn(
                f"{self.family}: divergence recovery gave up after "
                f"{self.max_attempts} attempts; returning the diverged "
                "state", stacklevel=2,
            )
            return None
        if self._ring:
            state, source = self._ring.pop(), "ring"
        else:
            state, source = self._cold_state(), "disk"
            if state is None:
                _tm.emit("recover", family=self.family,
                         attempt=self._attempts, gave_up=True,
                         reason="no_state")
                warnings.warn(
                    f"{self.family}: divergence recovery has no finite "
                    "state to roll back to; returning the diverged state",
                    stacklevel=2,
                )
                return None
        s = self.solver
        s._dt_scale = getattr(s, "_dt_scale", 1.0) * self.dt_scale
        new_fn = s._rebuild_chunk()
        t = float(state[self.time_index])
        nt = int(state[self.time_index + 1])
        if self.recorder is not None:
            self.recorder.rearm(nt)  # re-baseline: nt rewinds on rollback
        _tm.emit("recover", family=self.family, attempt=self._attempts,
                 source=source, t=t, nt=nt, dt_scale=s._dt_scale)
        warnings.warn(
            f"{self.family}: solver state diverged; rolled back to "
            f"t={t:.6g} (step {nt}, {source}) and re-driving with dt "
            f"clamped x{s._dt_scale:g} (attempt {self._attempts}/"
            f"{self.max_attempts})", stacklevel=2,
        )
        return state, new_fn


def coord_ckpt_cadence(solver, coord, publish):
    """Checkpoint cadence under the coordinator: the agreed ckpt vote
    commits the write at the boundary every rank voted on (the cli's
    on_sync periodic writer stands down when the coordinator is armed —
    see cli.py; two counters over the same cadence would double-write).
    Returns (ckpt_every, on_ckpt) — (0, None) when uncoordinated or no
    checkpoint path is set. The returned on_ckpt takes the loop's fault
    ledger (marked via `takes_ledger`) and hands it to the writer, so
    every agreed elastic commit persists the protocol state alongside
    the fields."""
    param = solver.param
    if coord is None or not param.tpu_checkpoint:
        return 0, None
    from ..utils import checkpoint as _ckpt

    writer = _ckpt.writer_for(param)

    def on_ckpt(s, ledger=None):
        publish(s)
        # stash the agreed ledger on the solver too: the cli's
        # END-OF-RUN write goes through save_elastic's _fault_ledger
        # fallback, so the final manifest keeps the last agreed
        # protocol state instead of silently dropping it
        solver._fault_ledger = ledger
        writer(param.tpu_checkpoint, solver, ledger=ledger)

    on_ckpt.takes_ledger = True

    def stash_ledger(ledger):
        # completion stash (no write): a run that finishes before the
        # first cadence boundary never called on_ckpt, so without this
        # the end-of-run manifest would drop the ledger entirely and
        # fail the `ckpt_fsck --survivors` pre-flight
        solver._fault_ledger = ledger

    on_ckpt.stash_ledger = stash_ledger
    return max(1, param.tpu_ckpt_every), on_ckpt


def make_recovery(solver, family: str, time_index: int, recorder=None):
    """RingRecovery from the solver's .par recovery keys; None when the
    ring is not armed (tpu_recover_ring 0 — the default, the historical
    terminate-on-NaN behavior)."""
    param = solver.param
    ring = getattr(param, "tpu_recover_ring", 0)
    if ring <= 0:
        return None
    # every family's state is (..., t, nt[, metrics]): metrics sits two
    # past the loop time when the telemetry vector rides the chunk
    mi = time_index + 2 if getattr(solver, "_metrics", False) else None
    rec = RingRecovery(
        solver, family, time_index, ring=ring,
        dt_scale=param.tpu_recover_dt_scale,
        max_attempts=param.tpu_recover_max,
        metrics_index=mi, recorder=recorder,
        ckpt_path=getattr(param, "tpu_checkpoint", ""),
    )
    led = getattr(solver, "_fault_ledger", None) or {}
    # resumed run: the attempt budget carries over (the dt clamp was
    # re-applied at load time) — a fleet that died mid-recovery cannot
    # restart with a fresh allowance against the same divergence
    rec._attempts = int(led.get("recover_attempts", 0))
    return rec
