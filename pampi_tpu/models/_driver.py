"""Shared chunked time-loop driver for the NS solvers.

Both NS-2D and NS-3D advance a carried state tuple through jitted chunk
calls (CHUNK device steps per host sync) with the same runtime-retry
protocol: a shape-specific pallas failure the dispatcher probe missed
rebuilds the chunk on the jnp path (same arithmetic) and retries the chunk —
inputs are unchanged because the loop is functional. This module is that
protocol's single home; the solvers supply the state arity and rebuild hook.
"""

from __future__ import annotations

import jax


def _is_transient_device_fault(exc) -> bool:
    """The axon-tunnelled chip intermittently raises UNAVAILABLE device
    errors on large programs that run fine on the next dispatch (measured:
    the same jitted solve failing then succeeding 3x in a row). Those are
    worth exactly one same-chunk retry; anything else is a real error."""
    return type(exc).__name__ == "JaxRuntimeError" and "UNAVAILABLE" in str(exc)


def drive_chunks(state, chunk_fn, te, time_index, bar, retry, on_state=None,
                 lookahead: int = 0):
    """Run `state = chunk_fn(*state)` while state[time_index] <= te
    (main.c:43-60 loop semantics: a step runs whenever t <= te at its start).

    retry() is called when a chunk raises: it returns a rebuilt chunk_fn to
    retry with, or None if there is no alternative path (the failure was not
    pallas's). In the None case a TRANSIENT device fault still gets one
    same-chunk retry (inputs are unchanged — the loop is functional) before
    re-raising. on_state(state) fires after every successful chunk — the
    host-sync / checkpoint hook point. Returns the final state (the first
    whose time exceeds te).

    lookahead > 0 pipelines the dispatch: up to lookahead+1 chunks stay in
    flight (the one being confirmed plus `lookahead` queued behind it — so
    lookahead=0 is one in flight, the serial case) and the host reads the
    loop time only from the OLDEST of them,
    so the per-chunk host<->device round trip (the dominant end-to-end cost
    under a high-latency tunnel — measured 27.7 vs the chip's 12.7 ms/step
    at dcavity 4096^2) overlaps the younger chunks' device execution. Safe
    by construction: a chunk dispatched past te is a device no-op (its own
    while-cond sees t > te and passes the state through), so speculative
    overshoot never advances the simulation, and the (undonated) input
    buffers stay alive for the retry path. On any failure the pipeline
    resets to the last CONFIRMED state — the one-shot retry protocol is
    unchanged, it just may re-dispatch the speculative tail. lookahead=0 is
    exactly the historical dispatch-then-sync loop."""
    if lookahead < 0:
        # cli.py validates the .par key; programmatic callers land here (a
        # negative value would popleft an empty deque and surface an
        # IndexError through the device-fault retry path)
        raise ValueError(f"lookahead must be >= 0 (got {lookahead})")
    transient_budget = 1
    if float(state[time_index]) > te:
        bar.stop()
        return state
    from collections import deque

    pending = deque()  # in-flight states, oldest first
    confirmed = state  # last state whose time read succeeded
    newest = state
    final = None
    while final is None:
        try:
            if len(pending) <= lookahead:
                newest = chunk_fn(*newest)
                pending.append(newest)
                continue
            old = pending.popleft()
            # force completion of the oldest in-flight chunk: async pallas
            # faults surface here, overlapped with the younger dispatches
            t_old = float(old[time_index])
        except Exception as exc:
            pending.clear()
            newest = confirmed
            new_fn = retry()
            if new_fn is None:
                if transient_budget > 0 and _is_transient_device_fault(exc):
                    import warnings

                    warnings.warn(
                        "transient TPU device fault; retrying the chunk once",
                        stacklevel=2,
                    )
                    transient_budget -= 1
                    continue
                raise
            chunk_fn = new_fn
            continue
        confirmed = old
        bar.update(t_old)
        if on_state is not None:
            on_state(old)
        # NaN loop time is terminal, not "not yet past te": an adaptive-dt
        # blow-up makes dt and then t NaN, every subsequent chunk is a
        # device no-op (its while-cond sees NaN <= te false), and
        # `t_old > te` is false for NaN — without this the loop would spin
        # forever on no-op dispatches (the dist solvers' `while t <= te`
        # already exits on NaN; this is the single-device twin). The
        # telemetry sentinel, when enabled, has already named the
        # last-good step by the time we land here.
        if t_old > te or t_old != t_old:
            final = old
    bar.stop()
    return final


def pallas_retry(solver, what: str):
    """The retry() hook for a solver with `_backend`/`_uses_pallas`/
    `_build_chunk`/`_chunk_fn`: falls back to the jnp chunk exactly once; a
    failure on the jnp path (or with pallas not even in play) re-raises.
    Covers the FUSED step-phase chunk too: `_uses_pallas` reports the fused
    kernels, and `_build_chunk(backend="jnp")` both selects the jnp solve
    AND stands the fused phases down (resolve_fuse_phases' backend
    contract), so one retry recovers from a failure in either kernel
    family."""

    def retry():
        if solver._backend == "jnp" or not solver._uses_pallas():
            return None  # the failing chunk never ran pallas — genuine error
        import warnings

        warnings.warn(
            f"pallas {what} failed at runtime; retrying this chunk on the "
            "jnp path", stacklevel=2,
        )
        solver._backend = "jnp"
        solver._chunk_fn = jax.jit(solver._build_chunk(backend="jnp"))
        return solver._chunk_fn

    return retry
