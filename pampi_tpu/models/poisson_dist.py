"""Distributed 2-D Poisson: red-black SOR over a 2-D device mesh.

Capability parity with the reference's distributed Poisson design
(assignment-4/src/solver.c:19-81 MPI skeleton + the complete 2-D model in
assignment-5/ex5-nazifkar/src/solver.c:406-660), TPU-first:

- The field lives as an interior-only (jmax, imax) global array sharded over
  the ("j","i") mesh. Ghost layers exist only INSIDE the kernel as an
  extended local block — there is no distributed assembly step at the end
  (commCollectResult is just reading the sharded array).
- Halo refresh is COMMUNICATION-AVOIDING (stencil2d.ca_*): one depth-2n
  exchange per n red-black iterations computed locally on a deep-halo
  extended block, with bitwise trajectory equality to the sequential
  red-black solver (the black pass sees post-red neighbour values exactly as
  the in-place sequential sweep does — redundant halo recompute yields
  identical values). The reference's 2-D MPI solver exchanges once per
  lexicographic sweep and accepts a different, block-hybrid trajectory
  (SURVEY.md §3.2); we keep exact RB equivalence and get device-count- AND
  n-independent trajectories. Extent-1 shards use the classic
  exchange-per-half-sweep fallback (rb_exchange_per_sweep).
- Residual: per-shard sum + `psum` (≙ MPI_Allreduce SUM, solver.c:651),
  normalized by global imax·jmax (solver.c:653 semantics).
- Physical-wall ghosts are owned by BC code on boundary shards only
  (`is_boundary` selects; exchange never writes them — PROC_NULL semantics).
- Checkerboard masks use GLOBAL (i+j) parity via the shard's mesh coordinates,
  so colouring is decomposition-invariant.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.comm import (
    CartComm,
    get_offsets,
    halo_exchange,
    master_print,
    reduction,
)
from ..parallel.quarters_dist import (
    pack_ext_to_q,
    q_exchange,
    quarters_dispatch,
    unpack_q_to_ext,
)
from ..parallel.stencil2d import (
    ca_halo,
    ca_inner,
    ca_masks,
    ca_rb_iters,
    ca_supported,
    neumann_masked,
    rb_exchange_per_sweep,
)
from ..utils import dispatch as _dispatch
from ..utils import flags as _flags
from ..utils.datio import write_matrix
from ..utils.params import Parameter
from ..utils.precision import resolve_dtype

PI = math.pi


class DistPoissonSolver:
    """Mesh-parallel Poisson solver; same .par interface as PoissonSolver."""

    def __init__(
        self, param: Parameter, comm: CartComm | None = None, problem: int = 2, dtype=None
    ):
        if dtype is None:
            dtype = resolve_dtype(param.tpu_dtype,
                                  record_key="poisson_dist_dtype")
        if param.tpu_solver in ("sor_lex", "sor_rba"):
            # the assignment-4 oracle modes are sequential by definition;
            # silently running the red-black path instead would defeat their
            # iteration-parity purpose
            raise ValueError(
                f"tpu_solver {param.tpu_solver} is a single-device oracle "
                "mode; distributed Poisson takes sor|mg|fft"
            )
        self.param = param
        self.dtype = dtype
        self.comm = comm if comm is not None else CartComm(
            ndims=2, extents=(param.jmax, param.imax),
            tiers=param.tpu_mesh_tiers,
        )
        self.imax, self.jmax = param.imax, param.jmax
        self.dx = param.xlength / param.imax
        self.dy = param.ylength / param.jmax
        # ragged pad-with-mask decomposition (≙ sizeOfRank remainder spread,
        # assignment-6/src/comm.c:19-22): ceil-divided uniform blocks whose
        # trailing dead cells the global-coordinate ca_masks already exclude
        # from updates, walls and residuals — any grid runs on any mesh
        self.jl, self.il = self.comm.local_shape(
            (self.jmax, self.imax), ragged=True
        )
        Pj, Pi = self.comm.dims
        self.ragged = (self.jl * Pj != self.jmax) or (self.il * Pi != self.imax)
        param = _dispatch.resolve_solver(
            param, obstacles=False, ragged=self.ragged,
        )
        self.param = param
        if self.ragged and param.tpu_solver in ("mg", "fft"):
            raise ValueError(
                f"tpu_solver {param.tpu_solver} needs a divisible grid/mesh "
                f"(grid {self.jmax}x{self.imax} on {self.comm.dims}); ragged "
                "pad-with-mask runs use tpu_solver sor"
            )
        self.problem = problem
        self._build()
        # interior-only sharded global field, initialized on-device
        self.p = self._init()
        self.res = None
        self.it = None
        self._started = False

    # -- kernel construction ------------------------------------------
    def _build(self):
        comm = self.comm
        param = self.param
        dtype = self.dtype
        jl, il = self.jl, self.il
        dx, dy = self.dx, self.dy
        dx2, dy2 = dx * dx, dy * dy
        idx2, idy2 = 1.0 / dx2, 1.0 / dy2
        factor = param.omg * 0.5 * (dx2 * dy2) / (dx2 + dy2)
        epssq = param.eps * param.eps
        itermax = param.itermax
        norm = float(self.imax * self.jmax)
        problem = self.problem

        # index/coordinate arithmetic stays in high precision regardless of the
        # compute dtype (bfloat16 rounds integers > 256); cast only the field
        idx_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32

        # communication-avoiding block size and halo depth (stencil2d.ca_*):
        # the solve carries a (jl+2H, il+2H) deep-halo extended block and pays
        # one depth-H exchange per n exact red-black iterations; extent-1
        # shards fall back to the classic exchange-per-half-sweep form; the
        # direct solvers (mg, fft) work on the plain halo-1 layout
        use_direct = param.tpu_solver in ("mg", "fft")
        supported = ca_supported(jl, il) and not use_direct
        n_ca = ca_inner(param, jl, il) if supported else 1
        H = ca_halo(n_ca, ragged=self.ragged) if supported else 1

        # -- quarter-layout production path (parallel/quarters_dist.py):
        # the single-chip headline kernel on every shard, one depth-n
        # quarter exchange per n iterations. layout=quarters forces it
        # (interpret-mode kernel off-TPU); auto takes it when pallas is live
        rb_q, qg, n_q, pallas_q = quarters_dispatch(
            param, self.jmax, self.imax, jl, il, dx, dy, dtype,
            "poisson_dist", plain_sor=not use_direct and not self.ragged,
        )
        if rb_q is None:
            tag = (
                f"jnp_ca ca{n_ca}" if supported else "jnp_rb_fallback"
            ) if not use_direct else f"direct_{param.tpu_solver}"
            if self.ragged:
                tag += " ragged"
            _dispatch.record("poisson_dist", tag)
        if param.tpu_solver == "mg":
            from ..ops.multigrid import make_dist_mg_solve_2d

            direct_solve, mg_pallas = make_dist_mg_solve_2d(
                comm, self.imax, self.jmax, jl, il, dx, dy,
                param.eps, itermax, dtype,
                stall_rtol=param.tpu_mg_stall_rtol,
                fused=param.tpu_mg_fused,
            )
            # per-shard Pallas smoothing needs check_vma relaxed, like the
            # quarters kernel
            pallas_q = pallas_q or mg_pallas
        elif param.tpu_solver == "fft":
            from ..ops.dctpoisson import make_dist_dct_solve_2d

            direct_solve = make_dist_dct_solve_2d(
                comm, self.imax, self.jmax, jl, il, dx, dy, dtype
            )

        def offsets():
            # local deep index a ↔ global extended index a - (halo-1) + offset
            joff = get_offsets("j", jl)
            ioff = get_offsets("i", il)
            return joff, ioff

        def analytic_ext(halo):
            """Analytic init at the GLOBAL extended index over a halo-`halo`
            block (initSolver:105-123): p = sin(4π·i·dx)+sin(4π·j·dy) —
            identical values the sequential init places at every position,
            including what are ghost positions here (values at out-of-domain
            halo positions are dead: masked from every update and read)."""
            joff, ioff = offsets()
            jj = (jnp.arange(jl + 2 * halo, dtype=idx_dtype) - (halo - 1) + joff) * dy
            ii = (jnp.arange(il + 2 * halo, dtype=idx_dtype) - (halo - 1) + ioff) * dx
            ext = jnp.sin(4.0 * PI * ii)[None, :] + jnp.sin(4.0 * PI * jj)[:, None]
            return ext.astype(dtype)

        def init_kernel():
            return analytic_ext(1)[1:-1, 1:-1]  # interior only

        def rhs_ext(halo):
            joff, ioff = offsets()
            ii = (jnp.arange(il + 2 * halo, dtype=idx_dtype) - (halo - 1) + ioff) * dx
            row = (
                jnp.sin(2.0 * PI * ii)
                if problem == 2
                else jnp.zeros(il + 2 * halo, idx_dtype)
            )
            return jnp.broadcast_to(
                row[None, :], (jl + 2 * halo, il + 2 * halo)
            ).astype(dtype)

        def solve_kernel(p_int, first: bool):
            """(jl, il) interior block -> (solved block, res, it).

            Ghost reconstruction: on the FIRST solve the walls carry the
            analytic init values (the sequential first sweep reads them,
            initSolver:105); on a resumed solve the walls carry the Neumann
            copies the previous iteration ended with, which equal an edge
            copy of the interior."""
            if rb_q is not None:
                return solve_kernel_quarters(p_int, first)
            m = ca_masks(jl, il, H, self.jmax, self.imax, dtype)
            p = analytic_ext(H).at[H:-H, H:-H].set(p_int)
            if not first:
                p = neumann_masked(p, m)
            rhs = rhs_ext(H)

            if use_direct:  # H == 1: plain extended blocks
                p, res, it = direct_solve(p, rhs)
                return p[1:-1, 1:-1], res, it

            def cond(carry):
                _, res, it = carry
                return jnp.logical_and(res >= epssq, it < itermax)

            def body(carry):
                p, _, it = carry
                if supported:
                    p = halo_exchange(p, comm, depth=H)
                    p, r2 = ca_rb_iters(p, rhs, n_ca, m, factor, idx2, idy2)
                else:
                    p, r2 = rb_exchange_per_sweep(
                        p, rhs, m, comm, factor, idx2, idy2,
                        ragged=self.ragged,
                    )
                res = reduction(r2, comm, "sum") / norm
                if _flags.debug():
                    master_print(comm, "{} Residuum: {}", it + (n_ca - 1), res)
                return p, res, it + n_ca

            init = (p, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32))
            p, res, it = lax.while_loop(cond, body, init)
            return p[H:-H, H:-H], res, it

        def solve_kernel_quarters(p_int, first: bool):
            """Quarter-layout production solve: the stacked stored plane of
            parallel/quarters_dist carried through the while_loop, one
            depth-n_q q_exchange per rb_q call (Pallas kernel on TPU, jnp
            twin otherwise). Same ghost-reconstruction policy as the grid
            path, on the halo-1 extended block before packing."""
            m1 = ca_masks(jl, il, 1, self.jmax, self.imax, dtype)
            ext = analytic_ext(1).at[1:-1, 1:-1].set(p_int)
            if not first:
                ext = neumann_masked(ext, m1)
            joff, ioff = offsets()
            qoffs = jnp.stack(
                [(joff // 2).astype(jnp.int32), (ioff // 2).astype(jnp.int32)]
            )
            rq = q_exchange(pack_ext_to_q(rhs_ext(1), qg), comm, qg)
            xq = pack_ext_to_q(ext, qg)

            def cond(carry):
                _, res, it = carry
                return jnp.logical_and(res >= epssq, it < itermax)

            def body(carry):
                xq, _, it = carry
                xq = q_exchange(xq, comm, qg)
                xq, r2 = rb_q(qoffs, xq, rq)
                res = reduction(r2, comm, "sum") / norm
                if _flags.debug():
                    master_print(comm, "{} Residuum: {}", it + (n_q - 1), res)
                return xq, res, it + n_q

            init = (xq, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32))
            xq, res, it = lax.while_loop(cond, body, init)
            return unpack_q_to_ext(xq, qg)[1:-1, 1:-1], res, it

        spec = P("j", "i")
        self._init_sm = jax.jit(
            comm.shard_map(init_kernel, in_specs=(), out_specs=spec)
        )
        out = (spec, P(), P())
        self._solve_first = jax.jit(
            comm.shard_map(
                lambda p: solve_kernel(p, True), in_specs=(spec,),
                out_specs=out, check_vma=not pallas_q,
            )
        )
        self._solve_resume = jax.jit(
            comm.shard_map(
                lambda p: solve_kernel(p, False), in_specs=(spec,),
                out_specs=out, check_vma=not pallas_q,
            )
        )

    def _init(self):
        return self._init_sm()

    # -- driver API ----------------------------------------------------
    def solve(self):
        import math
        import time

        from ..utils import telemetry as _tm

        t0 = time.perf_counter()
        fn = self._solve_resume if self._started else self._solve_first
        self._started = True
        self.p, res, it = fn(self.p)
        self.res, self.it = float(res), int(it)
        _tm.emit("solve", family="poisson_dist", iters=self.it,
                 res=self.res, wall_s=round(time.perf_counter() - t0, 4),
                 mesh=list(self.comm.dims))
        if not math.isfinite(self.res):
            _tm.emit("divergence", family="poisson_dist", res=self.res,
                     iters=self.it)
        return self.it, self.res

    def full_field(self) -> np.ndarray:
        """Reconstruct the reference's full (jmax+2, imax+2) array — interior
        from the sharded global array, Neumann edge ghosts, and the corner
        ghosts' untouched init values — for p.dat writer parity."""
        interior = self.comm.collect(self.p)
        jmax, imax = self.jmax, self.imax
        full = np.zeros((jmax + 2, imax + 2))
        # ragged decompositions carry trailing dead cells — strip them
        full[1:-1, 1:-1] = interior[:jmax, :imax]
        full[0, 1:-1] = full[1, 1:-1]
        full[-1, 1:-1] = full[-2, 1:-1]
        full[1:-1, 0] = full[1:-1, 1]
        full[1:-1, -1] = full[1:-1, -2]
        i = np.array([0, imax + 1])
        for jc in (0, jmax + 1):
            full[jc, i] = np.sin(4.0 * PI * i * self.dx) + np.sin(
                4.0 * PI * jc * self.dy
            )
        return full

    def write_result(self, path: str = "p.dat") -> None:
        # full_field's collect is collective — every process participates;
        # only rank 0 touches the file (≙ rank0 writeResult, main.c)
        full = self.full_field()
        if self.comm.is_master:
            write_matrix(full, path)
