from .poisson import PoissonSolver
