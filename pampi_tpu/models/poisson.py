"""2-D Poisson solver: red-black SOR with a residual-convergence loop in jit.

Capability parity with /root/reference/assignment-4 (initSolver:83, solve:126,
solveRB:179, solveRBA:240, writeResult:301) designed TPU-first:

- The whole convergence loop is ONE jitted `lax.while_loop` — carry (p, res, it),
  condition `res >= eps² && it < itermax` — so XLA keeps the field in device
  memory across iterations and fuses stencil + mask + reduction per half-sweep.
- All THREE reference solver variants are selectable modes:
  `tpu_solver sor` (default) → `solveRB`, the performance path (pallas on
  TPU); `tpu_solver sor_lex` → lexicographic `solve` as a scan/
  associative-scan oracle (`make_lex_step`; reproduces the committed golden
  p.dat byte-identically); `tpu_solver sor_rba` → `solveRBA` (separable-ω
  red-black, `make_rba_step`). All three converge in 2388 iterations on the
  reference's poisson.par, exactly matching the C binary (each variant
  compiled + run; see tests/test_poisson.py::test_solver_trio_iteration_parity).
- Equivalence policy for the performance path (SURVEY.md §7): match the
  *red-black* iteration trajectory exactly (same cells, same update order
  red→black, same residual accumulation & norm), and validate the converged
  field against the committed golden `p.dat` to discretization-level
  tolerance after removing the Neumann nullspace.

Init parity (initSolver:105-123): p = sin(4π·i·dx) + sin(4π·j·dy) on the FULL
array incl. ghosts; rhs = sin(2π·i·dx) for problem 2, else 0.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.sor import checkerboard_mask, lex_sweep, neumann_bc, sor_pass
from ..utils import flags as _flags
from ..utils.datio import write_matrix
from ..utils.params import Parameter
from ..utils.precision import resolve_dtype


def init_fields(param: Parameter, problem: int = 2, dtype=jnp.float64):
    """Initial p and rhs per assignment-4/src/solver.c:105-123."""
    imax, jmax = param.imax, param.jmax
    dx = param.xlength / imax
    dy = param.ylength / jmax
    i = np.arange(imax + 2)[None, :]
    j = np.arange(jmax + 2)[:, None]
    p = np.sin(2.0 * math.pi * i * dx * 2.0) + np.sin(2.0 * math.pi * j * dy * 2.0)
    if problem == 2:
        rhs = np.broadcast_to(np.sin(2.0 * math.pi * i * dx), p.shape).copy()
    else:
        rhs = np.zeros_like(p)
    return jnp.asarray(p, dtype=dtype), jnp.asarray(rhs, dtype=dtype)


def _use_pallas(backend: str, dtype=jnp.float32, probe=None) -> bool:
    """Backend-decision contract shared by every pallas-dispatched solver:
    explicit "pallas" forces, "auto" requires a real TPU, a Mosaic-lowerable
    dtype, and a passing one-time probe. `probe` defaults to the 2-D kernel's
    smoke test; the 3-D solver passes its own (models/ns3d._use_pallas_3d)."""
    if backend == "pallas":
        return True
    if backend != "auto" or jax.default_backend() != "tpu":
        return False
    if jnp.dtype(dtype).itemsize > 4:
        return False  # Mosaic has no f64; XLA emulates it, pallas can't
    if probe is None:
        from ..ops import sor_pallas as sp

        return sp.pltpu is not None and sp.probe_pallas()
    return probe()


def _try_quarters(imax, jmax, dx, dy, omega, dtype, n_inner, layout):
    """The quarters-layout resolution of make_rb_loop, factored out so the
    p-layout fold (models/ns2d) asks the solver's OWN decision instead of
    re-deriving the policy by hand: the built (rb_iter, brq, h) when the
    pallas solve smooths on the stacked quarters layout, None when
    checkerboard is the solve home (layout forced to checkerboard, odd
    dims under auto, or quarters construction VMEM-infeasible). A forced
    layout="quarters" propagates construction errors."""
    if layout not in ("auto", "quarters"):
        return None
    even = imax % 2 == 0 and jmax % 2 == 0
    if layout == "quarters" and not even:
        raise ValueError("quarters layout needs even imax and jmax")
    if not even:
        return None
    from ..ops import sor_pallas as sp

    # construction raises on pre-checked conditions (odd dims, f64)
    # and on VMEM infeasibility (quarters_feasible): forced layout
    # propagates the error, auto falls back to checkerboard; runtime
    # kernel failures surface at first dispatch and are handled by
    # the callers' jnp fallback
    try:
        return sp.make_rb_iter_tblock_quarters(
            imax, jmax, dx, dy, omega, dtype, n_inner=n_inner
        )
    except ValueError:
        if layout == "quarters":
            raise
        return None


def make_rb_loop(imax, jmax, dx, dy, omega, dtype, backend: str = "auto",
                 n_inner: int = 1, layout: str = "auto"):
    """Public dispatcher for loop-carried use: returns
    (step, prep, post, eff_inner) where prep/post convert the loop-carried
    array at the boundary (padded layout under pallas, identity under jnp)
    and eff_inner is the number of red-black iterations one `step` call
    ACTUALLY performs. The single decision point for the backend choice —
    bench.py and the solvers both go through here.

    n_inner > 1 selects the temporal-blocked pallas kernel: one `step` call
    performs n_inner red-black iterations (+BCs) in a single HBM sweep and
    reports the residual of the last one. The jnp path always steps one
    iteration at a time — eff_inner tells the caller which happened, so
    iteration accounting stays honest on both paths.

    layout (`tpu_sor_layout` .par key): "auto" dispatches the QUARTER
    decomposition kernel (ops/sor_quarters.py, 2.25× the checkerboard at
    4096² f32 — 107G vs 47.5G updates/s on v5e) when eligible (pallas
    active, even imax/jmax); "checkerboard" keeps the masked kernel (whose
    per-cell trajectory is numerically identical to the jnp path — quarters
    is ulp-equivalent, compiler fma/fusion differences only);
    "quarters" forces the quarter kernel (error if ineligible)."""
    if layout not in ("auto", "checkerboard", "quarters"):
        raise ValueError(
            f"2-D SOR layout must be auto|checkerboard|quarters, got "
            f"{layout!r} (octants is the 3-D layout)"
        )
    if _use_pallas(backend, dtype):
        from ..ops import sor_pallas as sp

        q = _try_quarters(imax, jmax, dx, dy, omega, dtype, n_inner, layout)
        if q is not None:
            rb_iter, brq, h = q
            norm = float(imax * jmax)

            def step(p_stacked, rhs_stacked):
                p_stacked, rsq = rb_iter(p_stacked, rhs_stacked)
                # bf16 storage accumulates the residual in f32 — keep
                # it there: the convergence scalar must not be
                # re-quantized to 8 mantissa bits on its way to the
                # res >= eps² check (the loop carries res at >= f32)
                return p_stacked, rsq / norm

            def prep(x):
                return sp.pad_quarters(x, brq, h)

            def post(xq):
                return sp.unpad_quarters(xq, jmax, imax, h)

            return step, prep, post, n_inner
        kernel = "tblock" if n_inner > 1 else "fused"
        try:
            step, prep, post = make_rb_step_padded(
                imax, jmax, dx, dy, omega, dtype, kernel=kernel,
                n_inner=n_inner,
            )
            return step, prep, post, n_inner
        except ValueError:
            if backend == "pallas":
                raise
            # VMEM-infeasible on this grid (tblock_feasible): the safe
            # fallback is jnp — the checkerboard kernel would crash Mosaic
            # at first dispatch on the same grids that trip quarters
            pass
    step = make_rb_step(imax, jmax, dx, dy, omega, dtype, backend="jnp")
    ident = lambda x: x  # noqa: E731
    return step, ident, ident, 1


def make_rb_step_padded(imax, jmax, dx, dy, omega, dtype, interpret=None,
                        kernel: str = "fused", n_inner: int = 4):
    """Pallas-backed red-black iteration on the PADDED layout
    (ops/sor_pallas.py): returns (step, pad, unpad) where step is
    (p_pad, rhs_pad) -> (p_pad', normalized res) incl. the Neumann ghost
    copy. The caller carries the padded array through its loop and converts
    at the boundary only.

    kernel: "tblock" (the production kernel: n_inner iterations per HBM
    sweep, double-buffered DMA, BCs fused inside; "fused" is an alias for
    n_inner=1) or "blocked" (two phases, one in-place sweep each — the
    simple aliased-I/O reference kernel)."""
    from ..ops import sor_pallas as sp

    norm = float(imax * jmax)
    if kernel == "fused":
        kernel, n_inner = "tblock", 1
    if kernel == "tblock":
        rb_iter, block_rows, halo = sp.make_rb_iter_tblock(
            imax, jmax, dx, dy, omega, dtype, n_inner=n_inner,
            interpret=interpret,
        )
        if rb_iter is None:
            raise ValueError("pallas backend unavailable")

        def step(p_pad, rhs_pad):
            p_pad, rsq = rb_iter(p_pad, rhs_pad)
            return p_pad, rsq / norm

        def pad(x):
            return sp.pad_array(x, block_rows, halo)

        def unpad(xp):
            return sp.unpad_array(xp, jmax, imax, halo)

        return step, pad, unpad

    rb_iter, block_rows = sp.make_rb_iter_pallas(
        imax, jmax, dx, dy, omega, dtype, interpret=interpret
    )
    if rb_iter is None:
        raise ValueError("pallas backend unavailable")

    def step(p_pad, rhs_pad):
        p_pad, rsq = rb_iter(p_pad, rhs_pad)
        return sp.neumann_bc_padded(p_pad, jmax, imax), rsq / norm

    def pad(x):
        return sp.pad_array(x, block_rows)

    def unpad(xp):
        return sp.unpad_array(xp, jmax, imax)

    return step, pad, unpad


def make_rb_step(imax, jmax, dx, dy, omega, dtype, backend: str = "auto",
                 factor=None):
    """Build one red-black SOR iteration: red half-sweep, black half-sweep
    (seeing red's updates), Neumann ghost copy, normalized residual.

    backend: "jnp" (masked fused-XLA passes), "pallas" (ops/sor_pallas.py
    blocked in-place kernel, pad/unpad per call — for loop-carried use go
    through make_rb_step_padded), or "auto" (pallas on TPU).
    factor: override for the relaxation factor (solveRBA's separable-ω
    association, make_rba_step); default is solveRB's (ω·0.5·dx²dy²)/(dx²+dy²)."""
    norm = float(imax * jmax)
    if factor is None and _use_pallas(backend, dtype):
        try:
            pstep, pad, unpad = make_rb_step_padded(
                imax, jmax, dx, dy, omega, dtype
            )
        except ValueError:
            if backend == "pallas":
                raise
            pstep = None  # VMEM-infeasible grid: jnp fallback below
        if pstep is not None:
            def step(p, rhs):
                p_pad, res = pstep(pad(p), pad(rhs))
                return unpad(p_pad), res

            return step

    dx2, dy2 = dx * dx, dy * dy
    idx2, idy2 = 1.0 / dx2, 1.0 / dy2
    if factor is None:
        factor = omega * 0.5 * (dx2 * dy2) / (dx2 + dy2)
    red = checkerboard_mask(jmax, imax, 0, dtype)
    black = checkerboard_mask(jmax, imax, 1, dtype)

    def step(p, rhs):
        p, r0 = sor_pass(p, rhs, red, factor, idx2, idy2)
        p, r1 = sor_pass(p, rhs, black, factor, idx2, idy2)
        p = neumann_bc(p)
        return p, (r0 + r1) / norm

    return step


def make_lex_step(imax, jmax, dx, dy, omega, dtype):
    """One lexicographic Gauss-Seidel SOR iteration + Neumann ghost copy —
    the reference's `solve` (assignment-4/src/solver.c:126-176) as a
    scan/associative-scan program (ops/sor.lex_sweep). Oracle-grade: always
    the jnp path (f64-capable), iteration-count parity with the C binary."""
    norm = float(imax * jmax)
    dx2, dy2 = dx * dx, dy * dy
    idx2, idy2 = 1.0 / dx2, 1.0 / dy2
    factor = omega * 0.5 * (dx2 * dy2) / (dx2 + dy2)

    def step(p, rhs):
        p, rsq = lex_sweep(p, rhs, factor, idx2, idy2)
        return neumann_bc(p), rsq / norm

    return step


def make_rba_step(imax, jmax, dx, dy, omega, dtype):
    """Red-black SOR with ω applied separately — the reference's `solveRBA`
    (assignment-4/src/solver.c:240-296). Identical cell visitation to
    `solveRB`; the only difference is the factor's floating-point
    association: ω·(0.5·dx²dy²/(dx²+dy²)) instead of (ω·0.5·dx²dy²)/(dx²+dy²).
    Oracle-grade jnp path, sharing make_rb_step's sweep body."""
    dx2, dy2 = dx * dx, dy * dy
    factor = omega * (0.5 * (dx2 * dy2) / (dx2 + dy2))
    return make_rb_step(imax, jmax, dx, dy, omega, dtype, backend="jnp",
                        factor=factor)


def make_padded_solver_fn(imax, jmax, dx, dy, omega, eps, itermax, dtype,
                          n_inner: int = 1, block_rows: int | None = None,
                          interpret: bool | None = None, flat: bool = False):
    """The rb convergence loop operating ENTIRELY in the sor_pallas padded
    layout: (p_pad, rhs_pad) -> (p_pad', res, it), no layout conversion
    inside. This is the p-layout fold of the fused NS-2D step
    (models/ns2d._build_fused_chunk): when the fused phase kernels share
    the solve's (block_rows, halo) geometry, the per-step pad/unpad passes
    around the solve vanish — p and rhs stay padded across the whole chunk.
    Input halo/tail rows may be UNDEFINED (the fused PRE never stores
    them): the tblock kernel consumes p/rhs only at
    logical-coordinate-gated cells (jnp.where selects, not multiplies), so
    garbage there cannot reach any stored value or the residual.

    Built on the checkerboard tblock kernel (the quarters layout is a
    different stacked data layout the fused kernels cannot share); raises
    ValueError when that kernel is unavailable or VMEM-infeasible. Same
    n_inner/flat contracts as make_solver_fn. Returns
    (solve, block_rows, halo)."""
    from ..ops import sor_pallas as sp
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax, dtype,
                    f"sor_tblock {imax}x{jmax}")
    eff = max(1, n_inner)
    rb_iter, block_rows, halo = sp.make_rb_iter_tblock(
        imax, jmax, dx, dy, omega, dtype, n_inner=eff,
        block_rows=block_rows, interpret=interpret,
    )
    if rb_iter is None:
        raise ValueError("pallas backend unavailable")
    norm = float(imax * jmax)
    epssq = eps * eps
    res_dtype = jnp.promote_types(dtype, jnp.float32)

    def solve(p_pad, rhs_pad):
        def cond(carry):
            _, res, it = carry
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(carry):
            p, _, it = carry
            p, rsq = rb_iter(p, rhs_pad)
            res = (rsq / norm).astype(res_dtype)
            if _flags.debug():
                jax.debug.print("{} Residuum: {}", it + (eff - 1), res)
            return p, res, it + eff

        init = (p_pad, jnp.asarray(1.0, res_dtype),
                jnp.asarray(0, jnp.int32))
        if flat:
            trips = -(-itermax // eff)
            return jax.lax.fori_loop(0, trips, lambda _t, c: body(c), init)
        return jax.lax.while_loop(cond, body, init)

    return solve, block_rows, halo


def make_solver_fn(imax, jmax, dx, dy, omega, eps, itermax, dtype,
                   backend="auto", n_inner: int = 1, method: str = "rb",
                   layout: str = "auto", flat: bool = False):
    """The full convergence loop as one jittable function (p0, rhs) -> (p, res, it).

    method: "rb" (the performance path, pallas on TPU), "lex" (the
    reference's lexicographic `solve` as an oracle mode), or "rba"
    (`solveRBA`, separable-ω red-black). lex/rba always run the jnp path.

    On the pallas backend the loop carries the PADDED array (one pad before,
    one unpad after — no per-iteration layout conversion). With n_inner > 1
    (pallas only) each loop step runs n_inner red-black iterations in one
    HBM sweep; convergence is then checked every n_inner iterations, so the
    solve may do up to n_inner-1 more iterations than a per-iteration check
    would (the extra iterations only lower the residual further). `it`
    reports the true iteration count on every path.

    `flat=True` (.par key tpu_flat_solve, round 5): run EXACTLY
    ceil(itermax/n) loop trips under `lax.fori_loop` with no res-gated
    cond. On configs whose solves always hit itermax (the north-star
    4096² cavity, the reference's own canal configs) the cond can never
    fire early, so the flat trajectory is BITWISE identical. On
    converging configs it overdrives to the cap (result still valid —
    extra sweeps only lower the residual; `res` is the final residual) —
    an extension of the n_inner check-granularity contract to the whole
    solve. Opt-in, default off. Perf note: measured NEUTRAL at 4096²
    (interleaved A/B, 19.01 vs 19.04 ms/step) — the loop trip overhead,
    not the residual gating, is the per-trip cost."""
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax, dtype, f"sor {imax}x{jmax}")
    epssq = eps * eps
    res_dtype = jnp.promote_types(dtype, jnp.float32)
    if method == "lex":
        step = make_lex_step(imax, jmax, dx, dy, omega, dtype)
        prep = post = lambda x: x  # noqa: E731
        eff = 1
    elif method == "rba":
        step = make_rba_step(imax, jmax, dx, dy, omega, dtype)
        prep = post = lambda x: x  # noqa: E731
        eff = 1
    else:
        step, prep, post, eff = make_rb_loop(
            imax, jmax, dx, dy, omega, dtype, backend, n_inner, layout
        )

    def solve(p0, rhs):
        rhs = prep(rhs)

        def cond(carry):
            _, res, it = carry
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(carry):
            p, _, it = carry
            p, res = step(p, rhs)
            # carry the convergence scalar at f32 or wider regardless of the
            # storage dtype (a scalar costs nothing; bf16 would re-quantize
            # the kernels' deliberately-f32 residual accumulation)
            res = res.astype(res_dtype)
            if _flags.debug():
                # ≙ -DDEBUG "%d Residuum: %e" (solver.c:169-171); 0-based
                # index of the last completed iteration, like the reference.
                # solveRBA additionally echoes omega (solver.c:289-291).
                if method == "rba":
                    jax.debug.print(
                        "{} Residuum: {} Omega: {}", it + (eff - 1), res, omega
                    )
                else:
                    jax.debug.print("{} Residuum: {}", it + (eff - 1), res)
            return p, res, it + eff

        init = (prep(p0), jnp.asarray(1.0, res_dtype),
                jnp.asarray(0, jnp.int32))
        if flat:
            trips = -(-itermax // eff)
            p, res, it = jax.lax.fori_loop(
                0, trips, lambda _t, c: body(c), init
            )
        else:
            p, res, it = jax.lax.while_loop(cond, body, init)
        return post(p), res, it

    return solve


class PoissonSolver:
    """Driver-facing wrapper (parity: the Solver struct + init/solve/writeResult)."""

    def __init__(self, param: Parameter, problem: int = 2, dtype=None):
        from ..utils.dispatch import resolve_solver

        param = resolve_solver(param, obstacles=False)
        if dtype is None:
            dtype = resolve_dtype(param.tpu_dtype,
                                  record_key="poisson_dtype")
        self.param = param
        self.dtype = dtype
        self.imax, self.jmax = param.imax, param.jmax
        self.dx = param.xlength / param.imax
        self.dy = param.ylength / param.jmax
        self.p, self.rhs = init_fields(param, problem, dtype)
        self._backend = "auto"
        self._solve = jax.jit(self._make_solve(backend="auto"))

    def _make_solve(self, backend: str):
        if self.param.tpu_solver == "mg":
            from ..ops.multigrid import make_mg_solve_2d

            return make_mg_solve_2d(
                self.imax, self.jmax, self.dx, self.dy,
                self.param.eps, self.param.itermax, self.dtype,
                stall_rtol=self.param.tpu_mg_stall_rtol, backend=backend,
                fused=self.param.tpu_mg_fused,
            )
        if self.param.tpu_solver == "fft":
            from ..ops.dctpoisson import make_dct_solve_2d

            return make_dct_solve_2d(
                self.imax, self.jmax, self.dx, self.dy, self.dtype
            )
        # the assignment-4 solver trio (solver.c:126/179/240): sor → solveRB
        # (the performance path), sor_lex → solve, sor_rba → solveRBA
        method = {"sor_lex": "lex", "sor_rba": "rba"}.get(
            self.param.tpu_solver, "rb"
        )
        return make_solver_fn(
            self.imax,
            self.jmax,
            self.dx,
            self.dy,
            self.param.omg,
            self.param.eps,
            self.param.itermax,
            self.dtype,
            backend=backend,
            n_inner=self.param.tpu_sor_inner,
            method=method,
            layout=self.param.tpu_sor_layout,
            flat=bool(self.param.tpu_flat_solve),
        )

    def solve(self):
        import math
        import time

        from ..utils import telemetry as _tm

        t0 = time.perf_counter()
        try:
            p, res, it = self._solve(self.p, self.rhs)
            # dispatch is async: force completion inside the try so a pallas
            # runtime fault surfaces here, not at the caller's readback
            out = int(it), float(res)
        except Exception:  # lint: allow(broad-except) — pallas runtime faults have no stable class; non-pallas paths re-raise below
            if self._backend == "jnp" or self.param.tpu_solver in (
                "mg", "fft", "sor_lex", "sor_rba",
            ):
                raise  # no pallas in play — genuine error, don't re-run it
            # shape-specific pallas failure the dispatcher probe missed:
            # fall back to the always-available jnp path (same arithmetic)
            self._backend = "jnp"
            self._solve = jax.jit(self._make_solve(backend="jnp"))
            p, res, it = self._solve(self.p, self.rhs)
            out = int(it), float(res)
        self.p = p
        # host-plane flight record: the (it, res) pair already crosses to
        # the host here, so the record costs nothing extra on-device
        _tm.emit("solve", family="poisson", iters=out[0], res=out[1],
                 wall_s=round(time.perf_counter() - t0, 4),
                 backend=self._backend)
        if not math.isfinite(out[1]):
            _tm.emit("divergence", family="poisson", res=out[1],
                     iters=out[0])
        return out

    def write_result(self, path: str = "p.dat") -> None:
        write_matrix(np.asarray(jax.device_get(self.p)), path)
