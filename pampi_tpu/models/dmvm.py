"""Distributed dense matrix-vector multiply with ring rotation of x —
the assignment-3a/3b capability, TPU-native.

Reference structure (/root/reference/assignment-3a/src/main.c): A row-block
scattered (:52), x broadcast (:54), then per rotation a local GEMV (:70-74)
followed by a ring shift of x to the next rank (`MPI_Sendrecv_replace` to
lowerNeighbor/from upperNeighbor, :77); MFLOP/s = 2·N²·iter/walltime/1e6
(:93-95). Assignment-3b is the same with `MPI_Isend/Irecv` posted around the
GEMV for communication/computation overlap (main.c:71-83).

TPU-native design — a ring-allgather matvec (the collective-matmul skeleton):
- A is row-sharded over a 1-D "r" mesh axis; x is BLOCK-sharded (each device
  holds N/R entries), not replicated.
- Each rotation multiplies the resident x block against the matching column
  block of the local A rows (`dynamic_slice`), then `ppermute`s the x block
  to rank+1 — the exact communication skeleton of the reference's ring, and
  of ring attention (SURVEY.md §5 long-context analog).
- After R rotations y_local = A_local · x exactly. DOCUMENTED DEVIATION: the
  shipped reference keeps a REPLICATED x and multiplies the full vector every
  rotation (main.c:70-74), doing R× redundant flops and computing R·A·x
  (and reading uninitialised x on rank 0 — the quirk list in SURVEY.md §7);
  we implement the blocked semantics the exercise is built around, so y=A·x.
- Overlap (the 3b exercise) comes from XLA's latency-hiding scheduler: the
  ppermute of the x block is independent of the GEMV's output, so with
  `overlap=True` the carry is double-buffered and XLA can overlap the
  collective with the matmul; the reference needed hand-rolled Isend/Irecv
  (with a latent overlap race, main.c:71-80 — impossible here by
  construction: ppermute is functional).

Init parity: a[i,j] = i+j, x[i] = i (main.c:45-50).

Kernel choice (measured, v5e): XLA's own gemv streams A at ~260-380 GB/s
at 8192² f32; hand-written Pallas alternatives (VPU lane-reduce over
(rows, cols) blocks, and an MXU dot_general accumulating over column
blocks) measured 0.5-0.75× that in the same session windows. The jnp
matmul IS the right TPU kernel here — the framework keeps it and spends
Pallas effort where it wins (the stencil kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import flags as _flags
from ..utils.precision import resolve_dtype
from ..utils.timing import get_timestamp


def _print_sum(s):
    import sys

    print("Sum: %f" % float(s), file=sys.stderr)  # lint: allow(print-call) — -DCHECK stderr parity (A3a dmvm.c:26-36)


def _fence(y) -> None:
    """Force device completion via a host readback of one LOCAL element —
    y[0] itself may live on another process under a multi-process launch."""
    _ = np.asarray(y.addressable_data(0)).ravel()[0]


def init_ax(N: int, dtype):
    """a[i,j] = i+j, x[i] = i (assignment-3a/src/main.c:45-50)."""
    i = np.arange(N, dtype=np.float64)
    a = i[:, None] + i[None, :]
    return jnp.asarray(a, dtype), jnp.asarray(i, dtype)


class SequentialDMVM:
    """Single-device timed y += A·x loop (≙ assignment-3a/src/dmvm.c:11-41)."""

    def __init__(self, N: int, dtype=None):
        self.N = N
        self.dtype = dtype or resolve_dtype("float32")
        self.a, self.x = init_ax(N, self.dtype)

        check = _flags.check()

        @jax.jit
        def run(a, x, iters):
            def body(_, y):
                # tie x to the carry with an exact no-op the compiler cannot
                # fold (0·y[0] is only provably 0 for finite y), so the
                # loop-invariant A·x cannot be hoisted out of the timed loop
                xdep = x * (1.0 + 0.0 * y[0])
                y = y + a @ xdep
                if check:
                    # ≙ -DCHECK (dmvm.c:26-36): print the running sum of y
                    # to stderr and zero y each iteration
                    jax.debug.callback(_print_sum, jnp.sum(y))
                    y = jnp.zeros_like(y)
                return y

            return lax.fori_loop(0, iters, body, jnp.zeros((N,), self.dtype))

        self._run = run

    def run(self, iters: int):
        """Timed single-dispatch loop; completion is forced by a host
        readback of one element (block_until_ready under the axon tunnel can
        return before device completion for queued work)."""
        # warm-up compiles the loop but executes ZERO iterations (iters is a
        # traced operand), so CHECK mode prints exactly `iters` Sum lines,
        # matching the reference's count
        y = self._run(self.a, self.x, 0)
        _ = float(y[0])
        t0 = get_timestamp()
        y = self._run(self.a, self.x, iters)
        _ = float(y[0])
        walltime = get_timestamp() - t0
        if _flags.check():
            # debug callbacks are async; drain them before returning so no
            # Sum line can be lost at process exit (and counts are exact)
            jax.effects_barrier()
        return y, walltime


class RingDMVM:
    """R-device ring matvec over a 1-D mesh (≙ assignment-3a/3b main loop)."""

    def __init__(
        self, N: int, devices=None, dtype=None, overlap: bool = True
    ):
        devs = devices if devices is not None else jax.devices()
        R = len(devs)
        if N % R:
            raise ValueError(f"N={N} not divisible by ring size {R}")
        self.N, self.R = N, R
        self.Nl = N // R  # rows per device
        self.Nb = N // R  # x block entries per device
        self.dtype = dtype or resolve_dtype("float32")
        self.mesh = Mesh(np.asarray(devs), ("r",))
        self.overlap = overlap
        a, x = init_ax(N, self.dtype)
        self.a = jax.device_put(a, NamedSharding(self.mesh, P("r", None)))
        self.x = jax.device_put(x, NamedSharding(self.mesh, P("r")))
        self._pass = jax.jit(self._build())

    def _build(self):
        R, Nl, Nb = self.R, self.Nl, self.Nb
        dtype = self.dtype
        perm = [(i, (i + 1) % R) for i in range(R)]
        overlap = self.overlap

        def kernel(a_local, x_blk, iters):
            r = lax.axis_index("r")

            def rot_body(rot, carry):
                y, xb = carry
                blk = (r - rot) % R
                start = (blk * Nb).astype(jnp.int32)
                cols = lax.dynamic_slice(
                    a_local, (jnp.asarray(0, jnp.int32), start), (Nl, Nb)
                )
                if overlap:
                    # double-buffer: the shift is independent of the GEMV, so
                    # XLA overlaps the collective with the compute (the 3b
                    # exercise, race-free)
                    xb_next = lax.ppermute(xb, "r", perm)
                    y = y + cols @ xb
                    xb = xb_next
                else:
                    y = y + cols @ xb
                    xb = lax.ppermute(xb, "r", perm)
                return y, xb

            def iter_body(_, carry):
                y, xb = carry
                # tie the x block to the carry (see SequentialDMVM) so the
                # per-iteration ring pass cannot be hoisted
                xb = xb * (1.0 + 0.0 * y[0])
                return lax.fori_loop(0, R, rot_body, (y, xb))

            y0 = jnp.zeros((Nl,), dtype)
            if hasattr(lax, "pcast"):  # newer jax: mark the accumulator
                y0 = lax.pcast(y0, ("r",), to="varying")  # mesh-varying
            y, _ = lax.fori_loop(0, iters, iter_body, (y0, x_blk))
            return y

        from ..parallel.comm import compat_shard_map

        return compat_shard_map(
            kernel,
            mesh=self.mesh,
            in_specs=(P("r", None), P("r"), None),
            out_specs=P("r"),
        )

    def run(self, iters: int):
        """Timed single-dispatch run; returns (y global, walltime, MFLOP/s).
        Completion forced by host readback (see SequentialDMVM.run).
        MFLOP/s = 2·N²·iter/walltime/1e6 (main.c:93-95) — for the blocked
        ring this counts exactly the executed flops."""
        y = self._pass(self.a, self.x, 1)
        _fence(y)  # warm-up/compile
        t0 = get_timestamp()
        y = self._pass(self.a, self.x, iters)
        _fence(y)
        walltime = get_timestamp() - t0
        mflops = 1.0e-6 * 2.0 * self.N * self.N * iters / walltime
        return y, walltime, mflops


def main(argv) -> int:
    """CLI parity: `<prog> <N> <iter>` prints `iter N MFlops walltime`
    (assignment-3a/src/main.c:25-34, 93-95) and appends a bench-harness CSV
    row `Ranks,NITER,N,MFlops,Time` (bash scripts/bench-node.sh:25)."""
    if len(argv) < 3:
        print(f"Usage: {argv[0]} <N> <iter>")  # lint: allow(print-call) — CLI usage line (reference main.c parity)
        return 0
    N, iters = int(argv[1]), int(argv[2])
    ndev = len(jax.devices())
    if ndev > 1 and N % ndev == 0:
        ring = RingDMVM(N)
        y, walltime, mflops = ring.run(iters)
        ranks = ring.R
    else:
        if ndev > 1:
            import sys as _sys

            print(  # lint: allow(print-call) — pre-run CLI warning (stderr)
                f"warning: N={N} not divisible by {ndev} devices; "
                "running single-device",
                file=_sys.stderr,
            )
        seq = SequentialDMVM(N)
        y, walltime = seq.run(iters)
        mflops = 1.0e-6 * 2.0 * N * N * iters / walltime
        ranks = 1
    print("%d %d %.2f %.2f" % (iters, N, mflops, walltime))  # lint: allow(print-call) — the bench headline the harness greps (A3a main.c:93-95)
    from ..parallel import multihost

    # read per RUN through the registered accessor (utils/flags.py) — the
    # bench harness exports PAMPI_CSV between dmvm invocations of one
    # process, so an import-time or first-call cache would miss it
    csv_path = _flags.env("PAMPI_CSV",
                          doc="dmvm bench CSV append path (rank 0 only)")
    if csv_path and multihost.is_master():
        # one CSV row per RUN, not per process (rank-0 convention)
        with open(csv_path, "a") as fh:
            fh.write("%d,%d,%d,%.2f,%.2f\n" % (ranks, iters, N, mflops, walltime))
    return 0
