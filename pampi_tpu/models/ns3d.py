"""NS-3D incompressible Navier-Stokes time-stepper (assignment-6 capability,
COMPLETED: the reference ships its distributed comm bodies as skeletons).

Pipeline parity with /root/reference/assignment-6/src/main.c:50-67:
computeTimestep → setBoundaryConditions → setSpecialBoundaryCondition →
computeFG → computeRHS → solve → adaptUV, t += dt while t <= te. (Unlike the
2-D driver there is NO normalizePressure in the loop.)

The pressure solve is 3-D red-black SOR (solve, solver.c:175-297): pass 0
visits (i+j+k) odd cells, pass 1 even (the reference's ksw/jsw/isw
checkerboard), factor = ω/2·(dx²dy²dz²)/(dy²dz²+dx²dz²+dx²dy²), 6-face
Neumann ghost copies after both passes, residual normalized by
imax·jmax·kmax. DOCUMENTED DEVIATION: the reference never resets `res`
inside the while loop (solver.c:203-230) — an accumulation bug flagged in
SURVEY.md §2.1; we reset per iteration (and the parity oracle used by the
tests is the reference built with the same one-line fix).

Time loop runs on-device in host-synced chunks like NS-2D.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops import ns3d as ops
from ..utils import faultinject as _fi
from ..utils import flags as _flags
from ..utils import telemetry as _tm
from ._driver import clamped_dt
from ..utils.grid import Grid
from ..utils.params import Parameter, validate_obstacle_layout
from ..utils.precision import resolve_dtype
from ..utils.progress import Progress
from ..utils.vtkio import VtkWriter


def checkerboard_mask_3d(kmax, jmax, imax, parity, dtype):
    """Interior mask where (i+j+k) % 2 == parity (1-based indices). Pass 0
    of the reference's sweep visits parity 1 (odd), pass 1 parity 0."""
    kk = jnp.arange(1, kmax + 1, dtype=jnp.int32)[:, None, None]
    jj = jnp.arange(1, jmax + 1, dtype=jnp.int32)[None, :, None]
    ii = jnp.arange(1, imax + 1, dtype=jnp.int32)[None, None, :]
    return (((ii + jj + kk) % 2) == parity).astype(dtype)


def neumann_faces_3d(p):
    """6-face pressure ghost copy (solve's commIsBoundary blocks,
    solver.c:233-279); tangential ranges [1:-1], edges/corners untouched."""
    p = p.at[0, 1:-1, 1:-1].set(p[1, 1:-1, 1:-1])  # front
    p = p.at[-1, 1:-1, 1:-1].set(p[-2, 1:-1, 1:-1])  # back
    p = p.at[1:-1, 0, 1:-1].set(p[1:-1, 1, 1:-1])  # bottom
    p = p.at[1:-1, -1, 1:-1].set(p[1:-1, -2, 1:-1])  # top
    p = p.at[1:-1, 1:-1, 0].set(p[1:-1, 1:-1, 1])  # left
    p = p.at[1:-1, 1:-1, -1].set(p[1:-1, 1:-1, -2])  # right
    return p


def interior_residual_3d(p, rhs, idx2, idy2, idz2):
    """Pointwise residual r = rhs - lap(p) on the interior — the single home
    of the 7-point stencil expression (sor_pass_3d and ops/multigrid share
    it)."""
    lap = (
        (p[1:-1, 1:-1, 2:] - 2.0 * p[1:-1, 1:-1, 1:-1] + p[1:-1, 1:-1, :-2]) * idx2
        + (p[1:-1, 2:, 1:-1] - 2.0 * p[1:-1, 1:-1, 1:-1] + p[1:-1, :-2, 1:-1]) * idy2
        + (p[2:, 1:-1, 1:-1] - 2.0 * p[1:-1, 1:-1, 1:-1] + p[:-2, 1:-1, 1:-1]) * idz2
    )
    return rhs[1:-1, 1:-1, 1:-1] - lap


def sor_pass_3d(p, rhs, mask, factor, idx2, idy2, idz2):
    """One masked half-sweep of the 7-point stencil (solver.c:210-229)."""
    r = interior_residual_3d(p, rhs, idx2, idy2, idz2) * mask
    p = p.at[1:-1, 1:-1, 1:-1].add(-factor * r)
    return p, jnp.sum(r * r)


def sor_coefficients_3d(dx, dy, dz, omega):
    """(factor, idx2, idy2, idz2) of the 3-D SOR update (solver.c:186-196) —
    the single source of truth for both the single-device and distributed
    solvers."""
    dx2, dy2, dz2 = dx * dx, dy * dy, dz * dz
    factor = omega * 0.5 * (dx2 * dy2 * dz2) / (dy2 * dz2 + dx2 * dz2 + dx2 * dy2)
    return factor, 1.0 / dx2, 1.0 / dy2, 1.0 / dz2


def write_vtk_result(param, grid, fields, path=None, fmt: str = "ascii") -> None:
    """VTK output (main.c:100-106): scalar pressure + vector velocity.
    fields = (ug, vg, wg, pg) cell-centered global arrays."""
    ug, vg, wg, pg = fields
    problem = param.name.replace("3d", "")
    writer = VtkWriter(problem, grid, fmt=fmt, path=path)
    writer.scalar("pressure", pg)
    writer.vector("velocity", ug, vg, wg)
    writer.close()


def _use_pallas_3d(backend: str, dtype) -> bool:
    """models/poisson._use_pallas with the 3-D kernel's probe."""
    from .poisson import _use_pallas

    def probe():
        from ..ops import sor3d_pallas as sp3

        return sp3.pltpu is not None and sp3.probe_pallas_3d()

    return _use_pallas(backend, dtype, probe=probe)


def make_pressure_solve_3d(imax, jmax, kmax, dx, dy, dz, omega, eps, itermax,
                           dtype, backend: str = "auto", n_inner: int = 1,
                           solver: str = "sor", layout: str = "auto",
                           stall_rtol=None, mg_fused: str = "off"):
    """Convergence loop for the 3-D pressure solve. solver="sor" (default,
    the reference's algorithm): backend="auto" dispatches to the fused Pallas
    kernel (ops/sor3d_pallas.py) on a real TPU chip and to the jnp half-sweep
    composition otherwise; both carry (p, res, it) through a
    `lax.while_loop`. Under pallas the loop carries the PADDED array (one pad
    before, one unpad after — no per-iteration layout conversion); with
    n_inner > 1 each loop step runs n_inner red-black iterations in one HBM
    sweep and observes the last one's residual, so `it` advances by n_inner
    per step (honest iteration accounting). solver="mg": geometric multigrid
    V-cycles (ops/multigrid.py), same stopping contract, `it` counts
    cycles."""
    if solver == "mg":
        from ..ops.multigrid import make_mg_solve_3d

        return make_mg_solve_3d(imax, jmax, kmax, dx, dy, dz, eps, itermax,
                                dtype, stall_rtol=stall_rtol,
                                backend=backend, fused=mg_fused)
    if solver == "fft":
        from ..ops.dctpoisson import make_dct_solve_3d

        return make_dct_solve_3d(imax, jmax, kmax, dx, dy, dz, dtype)
    if solver != "sor":
        raise ValueError(
            f"NS pressure solve supports sor|mg|fft, got {solver!r} "
            "(sor_lex/sor_rba are Poisson-only oracle modes)"
        )
    norm = float(imax * jmax * kmax)
    epssq = eps * eps

    if layout not in ("auto", "checkerboard", "octants"):
        raise ValueError(
            f"3-D SOR layout must be auto|checkerboard|octants, got "
            f"{layout!r} (quarters is the 2-D layout)"
        )
    use_pallas = _use_pallas_3d(backend, dtype)
    even = imax % 2 == 0 and jmax % 2 == 0 and kmax % 2 == 0
    if layout == "octants" and not even:
        raise ValueError("octant layout needs even imax, jmax, kmax")
    if use_pallas and layout in ("auto", "octants") and even:
        # the OCTANT layout (ops/sor_octants.py): 4.9× the checkerboard
        # kernel at 128³ f32 on v5e (0.257 vs 1.25 ms/iter, k=4)
        from ..ops import sor3d_pallas as sp3

        bko = sp3.pick_block_k_octants(kmax, jmax, imax, dtype, n_inner)
        degenerate = sp3.block_k_octants_degenerate(
            bko, kmax, jmax, imax, dtype, n_inner
        )
        if not degenerate:
            rb_iter, bko, _h = sp3.make_rb_iter_tblock_3d_octants(
                imax, jmax, kmax, dx, dy, dz, omega, dtype,
                n_inner=n_inner, block_k=bko,
            )
            if rb_iter is not None:
                return sp3.make_octants_solve_loop(
                    rb_iter, bko, n_inner, norm, eps, itermax,
                    kmax, jmax, imax, dtype,
                )
        elif layout == "octants":
            raise ValueError(
                "octant layout: VMEM budget degenerates block_k at this "
                "in-plane size; use layout=auto or checkerboard"
            )
    if use_pallas and backend != "pallas":
        from ..ops import sor3d_pallas as sp3

        # in-plane size so large the VMEM budget forces block_k below the
        # halo depth: the kernel would recompute halos >3x over and likely
        # overflow VMEM — the jnp path is the better program
        bk = sp3.pick_block_k(kmax, jmax, imax, dtype, n_inner)
        use_pallas = not sp3.block_k_degenerate(bk, kmax, n_inner)

    if use_pallas:
        from ..ops import sor3d_pallas as sp3

        rb_iter, block_k = sp3.make_rb_iter_tblock_3d(
            imax, jmax, kmax, dx, dy, dz, omega, dtype, n_inner=n_inner
        )
        if rb_iter is None:
            raise ValueError("pallas 3-D backend unavailable")
        return sp3.make_tblock_solve_loop(
            rb_iter, block_k, n_inner, norm, eps, itermax,
            kmax, jmax, imax, dtype,
        )

    factor, idx2, idy2, idz2 = sor_coefficients_3d(dx, dy, dz, omega)
    odd = checkerboard_mask_3d(kmax, jmax, imax, 1, dtype)
    even = checkerboard_mask_3d(kmax, jmax, imax, 0, dtype)

    def solve(p, rhs):
        def cond(c):
            _, res, it = c
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(c):
            p, _, it = c
            p, r0 = sor_pass_3d(p, rhs, odd, factor, idx2, idy2, idz2)
            p, r1 = sor_pass_3d(p, rhs, even, factor, idx2, idy2, idz2)
            p = neumann_faces_3d(p)
            if _flags.debug():
                jax.debug.print("{} Residuum: {}", it, (r0 + r1) / norm)
            return p, (r0 + r1) / norm, it + 1

        return lax.while_loop(
            cond, body, (p, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32))
        )

    return solve


class NS3DSolver:
    """Driver-facing NS-3D solver (≙ assignment-6 Solver struct + main loop)."""

    CHUNK = 32

    def __init__(self, param: Parameter, dtype=None):
        from ..utils.dispatch import resolve_solver

        param = resolve_solver(param, obstacles=bool(param.obstacles.strip()))
        if dtype is None:
            dtype = resolve_dtype(param.tpu_dtype,
                                  record_key="ns3d_dtype")
        self.param = param
        self.dtype = dtype
        self.grid = Grid(
            imax=param.imax,
            jmax=param.jmax,
            kmax=param.kmax,
            xlength=param.xlength,
            ylength=param.ylength,
            zlength=param.zlength,
        )
        g = self.grid
        shape = (g.kmax + 2, g.jmax + 2, g.imax + 2)
        self.u = jnp.full(shape, param.u_init, dtype)
        self.v = jnp.full(shape, param.v_init, dtype)
        self.w = jnp.full(shape, param.w_init, dtype)
        self.p = jnp.full(shape, param.p_init, dtype)
        inv_sqr_sum = 1.0 / g.dx**2 + 1.0 / g.dy**2 + 1.0 / g.dz**2
        self.dt_bound = 0.5 * param.re / inv_sqr_sum
        self.t = 0.0
        self.nt = 0
        self._backend = "auto"
        self._fused = False  # set by _build_chunk (fused-phase dispatch)
        self._dt_scale = 1.0  # recovery dt clamp (models/_driver.clamped_dt)
        # flag-field obstacles (ops/obstacle3d.py): static geometry -> static
        # masks baked into the traced step as constants (branch-free)
        if param.obstacles.strip():
            if param.tpu_solver == "fft":
                raise ValueError(
                    "tpu_solver fft cannot solve obstacle flag fields (the "
                    "stencil is not constant-coefficient); use sor or mg"
                )
            validate_obstacle_layout(param.tpu_sor_layout)
            from ..ops import obstacle3d as obst3

            fluid = obst3.build_fluid_3d(
                g.imax, g.jmax, g.kmax, g.dx, g.dy, g.dz, param.obstacles
            )
            self.masks = obst3.make_masks_3d(
                fluid, g.dx, g.dy, g.dz, param.omg, dtype
            )
        else:
            self.masks = None
        t0 = time.perf_counter()
        # fault-injection generation: taken here and in _rebuild_chunk
        # only (see models/ns2d.py for the pallas-fallback rationale)
        self._field_faults = _fi.take_field_faults()
        self._chunk_fn = jax.jit(self._build_chunk())
        from ..utils import dispatch as _dispatch

        _tm.emit("build", family="ns3d",
                 grid=[g.kmax, g.jmax, g.imax],
                 trace_wall_s=round(time.perf_counter() - t0, 3),
                 phases=_dispatch.last("ns3d_phases"))

    def _uses_pallas(self) -> bool:
        if self._fused:
            return True  # the fused step-phase pair is a pallas kernel
        if self.param.tpu_solver == "fft":
            return False  # fft chunks contain no pallas kernel
        # sor AND mg go through the probe: mg's fine-level smoother
        # dispatches the 3-D tblock kernel on large levels (round 4)
        return _use_pallas_3d(self._backend, self.dtype)

    def _make_solve(self, backend: str):
        """The 3-D pressure-solve closure for one backend — shared by the
        jnp step chain and the fused-phase chunk."""
        param = self.param
        g = self.grid
        dtype = self.dtype
        dx, dy, dz = g.dx, g.dy, g.dz
        masks = self.masks
        if masks is not None and param.tpu_solver == "mg":
            # 3-D obstacle multigrid (round 4): rediscretized
            # eps-coefficient operator per level, exact dense bottom
            from ..ops.multigrid import make_obstacle_mg_solve_3d

            solve = make_obstacle_mg_solve_3d(
                g.imax, g.jmax, g.kmax, dx, dy, dz,
                param.eps, param.itermax, masks, dtype,
                stall_rtol=param.tpu_mg_stall_rtol, backend=backend,
                fused=param.tpu_mg_fused,
            )
        elif masks is not None:
            from ..ops.obstacle3d import make_obstacle_solver_fn_3d

            solve = make_obstacle_solver_fn_3d(
                g.imax, g.jmax, g.kmax, dx, dy, dz,
                param.eps, param.itermax, masks, dtype,
                backend=backend, n_inner=param.tpu_sor_inner,
            )
        else:
            solve = make_pressure_solve_3d(
                g.imax, g.jmax, g.kmax, dx, dy, dz,
                param.omg, param.eps, param.itermax, dtype,
                backend=backend, n_inner=param.tpu_sor_inner,
                solver=param.tpu_solver,
                layout=param.tpu_sor_layout,
                stall_rtol=param.tpu_mg_stall_rtol,
                mg_fused=param.tpu_mg_fused,
            )
        return solve

    def _build_step(self, backend: str = "auto", instrumented: bool = False):
        """One traced timestep. instrumented=True returns the SAME pipeline
        with the solve's discarded outputs exposed —
        (u, v, w, p, t, nt, res, it, dt) — the telemetry chunk's source
        (the NS-2D convention, models/ns2d.py)."""
        param = self.param
        g = self.grid
        dtype = self.dtype
        dx, dy, dz = g.dx, g.dy, g.dz
        masks = self.masks
        solve = self._make_solve(backend)
        bcs = {
            "top": param.bcTop,
            "bottom": param.bcBottom,
            "left": param.bcLeft,
            "right": param.bcRight,
            "front": param.bcFront,
            "back": param.bcBack,
        }
        adaptive = param.tau > 0.0
        problem = param.name.replace("3d", "")
        dt_scale = self._dt_scale  # 1.0 = identity (recovery rebuilds clamp)
        faults = getattr(self, "_field_faults", ())

        def step(u, v, w, p, t, nt):
            u, v, w, p = _fi.apply_field_faults(faults, nt, u=u, v=v, w=w,
                                                p=p)
            if adaptive:
                dt = ops.compute_timestep_3d(
                    u, v, w, jnp.asarray(self.dt_bound, dtype), dx, dy, dz, param.tau
                )
            else:
                dt = jnp.asarray(param.dt, dtype)
            dt = clamped_dt(dt, dt_scale)
            u, v, w = ops.set_boundary_conditions_3d(u, v, w, bcs)
            if problem == "dcavity":
                u = ops.set_special_bc_dcavity_3d(u)
            elif problem == "canal":
                u = ops.set_special_bc_canal_3d(u)
            if masks is not None:
                from ..ops.obstacle3d import (
                    adapt_uvw_obstacle,
                    apply_obstacle_velocity_bc_3d,
                    mask_fgh,
                )

                u, v, w = apply_obstacle_velocity_bc_3d(u, v, w, masks)
            f, g_, h = ops.compute_fgh(
                u, v, w, dt, param.re, param.gx, param.gy, param.gz,
                param.gamma, dx, dy, dz,
            )
            if masks is not None:
                f, g_, h = mask_fgh(f, g_, h, u, v, w, masks)
            rhs = ops.compute_rhs(f, g_, h, dt, dx, dy, dz)
            p, _res, _it = solve(p, rhs)
            if masks is not None:
                u, v, w = adapt_uvw_obstacle(
                    u, v, w, f, g_, h, p, dt, dx, dy, dz, masks
                )
            else:
                u, v, w = ops.adapt_uvw(u, v, w, f, g_, h, p, dt, dx, dy, dz)
            time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            t_next = t + dt.astype(time_dtype)
            if _flags.verbose():
                # printed AFTER t += dt, matching A6 main.c:58-62
                jax.debug.print("TIME {} , TIMESTEP {}", t_next, dt)
            if instrumented:
                return u, v, w, p, t_next, nt + 1, _res, _it, dt
            return u, v, w, p, t_next, nt + 1

        return step

    def _build_fused_chunk(self, backend: str, metrics: bool = False,
                           te_arg: bool = False, kfuse: int = 1):
        """The 3-D fused-phase chunk (ops/ns3d_fused.py): the non-solve
        phases run as two Pallas kernels around the solve, the loop carries
        u/v/w in the padded layout plus the running (umax, vmax, wmax),
        and the timestep is scalar math (ops/ns3d.cfl_dt_3d). None when the
        fused path is not dispatched — the caller falls back to the jnp
        chunk. Obstacle flag fields compose in-kernel (the 2-D template):
        the global flag rides as a baked padded constant."""
        from ..ops.ns3d_fused import probe_fused_3d
        from ..utils.dispatch import record, resolve_fuse_phases

        param = self.param
        if not resolve_fuse_phases(
            param, backend, self.dtype, probe_fused_3d, "ns3d_phases",
        ):
            return None
        from ..ops import ns3d_fused as nf3

        g = self.grid
        dtype = self.dtype
        dx, dy, dz = g.dx, g.dy, g.dz
        try:
            pre, post, pad3, unpad3, _h = nf3.make_fused_step_3d(
                param, g.kmax, g.jmax, g.imax, dx, dy, dz, dtype,
                fluid=None if self.masks is None else self.masks.fluid,
            )
        except ValueError as exc:  # VMEM-infeasible geometry
            record("ns3d_phases", f"jnp ({exc})")
            return None
        solve = self._make_solve(backend)
        adaptive = param.tau > 0.0
        dt_scale = self._dt_scale  # 1.0 = identity (recovery rebuilds clamp)
        faults = getattr(self, "_field_faults", ())
        te_static = param.te
        chunk = param.tpu_chunk or self.CHUNK
        offs = jnp.zeros((3,), jnp.int32)
        dt_bound = jnp.asarray(self.dt_bound, dtype)
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32

        def step(up, vp, wp, p, t, nt, umax, vmax, wmax):
            up, vp, wp, p = _fi.apply_field_faults(faults, nt, u=up, v=vp,
                                                   w=wp, p=p)
            if adaptive:
                dt = ops.cfl_dt_3d(umax, vmax, wmax, dt_bound, dx, dy, dz,
                                   param.tau)
            else:
                dt = jnp.asarray(param.dt, dtype)
            dt = clamped_dt(dt, dt_scale)
            dt11 = jnp.full((1, 1), dt, dtype)
            up, vp, wp, fp, gp, hp, rhsp = pre(offs, dt11, up, vp, wp)
            rhs = unpad3(rhsp)
            p, _res, _it = solve(p, rhs)
            up, vp, wp, umax, vmax, wmax = post(
                offs, dt11, up, vp, wp, fp, gp, hp, pad3(p)
            )
            t_next = t + dt.astype(time_dtype)
            if _flags.verbose():
                jax.debug.print("TIME {} , TIMESTEP {}", t_next, dt)
            if metrics:
                return (up, vp, wp, p, t_next, nt + 1, umax, vmax, wmax,
                        _res, _it, dt)
            return up, vp, wp, p, t_next, nt + 1, umax, vmax, wmax

        def chunk_fn(u, v, w, p, t, nt, *te_in):
            # te_arg builds take the end time as a TRACED trailing arg
            # (the fleet's per-lane te carry); the default closes over
            # the baked constant — the byte-identical historical trace
            te = te_in[0] if te_in else te_static
            up, vp, wp = pad3(u), pad3(v), pad3(w)
            umax = jnp.max(jnp.abs(u))
            vmax = jnp.max(jnp.abs(v))
            wmax = jnp.max(jnp.abs(w))

            def cond(c):
                return jnp.logical_and(c[4] <= te, c[9] < chunk)

            if kfuse > 1:
                # K-step fused trips (ISSUE 17): one scan advances K
                # gated steps (frozen identity past te) per while trip
                def kblock(c, _):
                    def live(c):
                        return step(*c)

                    return lax.cond(c[4] <= te, live, lambda c: c, c), None

                def body(c):
                    up, vp, wp, p, t, nt, um, vm, wm, k = c
                    (up, vp, wp, p, t, nt, um, vm, wm), _ = lax.scan(
                        kblock, (up, vp, wp, p, t, nt, um, vm, wm), None,
                        length=kfuse)
                    return up, vp, wp, p, t, nt, um, vm, wm, k + kfuse
            else:
                def body(c):
                    up, vp, wp, p, t, nt, um, vm, wm, k = c
                    up, vp, wp, p, t, nt, um, vm, wm = step(
                        up, vp, wp, p, t, nt, um, vm, wm
                    )
                    return up, vp, wp, p, t, nt, um, vm, wm, k + 1

            up, vp, wp, p, t, nt, _um, _vm, _wm, _k = lax.while_loop(
                cond, body,
                (up, vp, wp, p, t, nt, umax, vmax, wmax,
                 jnp.asarray(0, jnp.int32)),
            )
            return unpad3(up), unpad3(vp), unpad3(wp), p, t, nt

        def chunk_fn_metrics(u, v, w, p, t, nt, m, *te_in):
            # the telemetry twin: the carried CFL maxima and the solve's
            # res/it pack into the in-band vector at the chunk boundary
            te = te_in[0] if te_in else te_static
            up, vp, wp = pad3(u), pad3(v), pad3(w)
            umax = jnp.max(jnp.abs(u))
            vmax = jnp.max(jnp.abs(v))
            wmax = jnp.max(jnp.abs(w))

            def cond(c):
                return jnp.logical_and(c[4] <= te, c[9] < chunk)

            if kfuse > 1:
                # per-step metrics_step (POST-step nt) inside the live
                # branch — divergence keeps step resolution in the K-block
                def kblock(c, _):
                    def live(c):
                        (up, vp, wp, p, t, nt, um, vm, wm,
                         res, it, dtv, bad) = c
                        (up, vp, wp, p, t, nt, um, vm, wm,
                         res, it, dtv) = step(up, vp, wp, p, t, nt,
                                              um, vm, wm)
                        res, it, dtv, _u, _v, _w, bad = _tm.metrics_step(
                            bad, nt, res, it, dtv, um, vm, wm)
                        return (up, vp, wp, p, t, nt, um, vm, wm,
                                res, it, dtv, bad)

                    return lax.cond(c[4] <= te, live, lambda c: c, c), None

                def body(c):
                    (up, vp, wp, p, t, nt, um, vm, wm, k,
                     res, it, dtv, bad) = c
                    (up, vp, wp, p, t, nt, um, vm, wm,
                     res, it, dtv, bad), _ = lax.scan(
                        kblock,
                        (up, vp, wp, p, t, nt, um, vm, wm,
                         res, it, dtv, bad),
                        None, length=kfuse)
                    return (up, vp, wp, p, t, nt, um, vm, wm, k + kfuse,
                            res, it, dtv, bad)
            else:
                def body(c):
                    (up, vp, wp, p, t, nt, um, vm, wm, k,
                     res, it, dtv, bad) = c
                    (up, vp, wp, p, t, nt, um, vm, wm,
                     res, it, dtv) = step(up, vp, wp, p, t, nt, um, vm, wm)
                    # maxima stay native-dtype in the carry (the CFL
                    # scalars)
                    res, it, dtv, _u, _v, _w, bad = _tm.metrics_step(
                        bad, nt, res, it, dtv, um, vm, wm)
                    return (up, vp, wp, p, t, nt, um, vm, wm, k + 1,
                            res, it, dtv, bad)

            (up, vp, wp, p, t, nt, um, vm, wm, _k,
             res, it, dtv, bad) = lax.while_loop(
                cond, body,
                (up, vp, wp, p, t, nt, umax, vmax, wmax,
                 jnp.asarray(0, jnp.int32),
                 m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT], m[_tm.M_BAD]),
            )
            return (unpad3(up), unpad3(vp), unpad3(wp), p, t, nt,
                    _tm.metrics_pack(res, it, dtv, um, vm, wm, bad))

        return chunk_fn_metrics if metrics else chunk_fn

    def _build_chunk(self, backend: str = "auto", te_arg: bool = False):
        # trace-time telemetry gate (utils/flags.py convention): unset means
        # the chunk below is byte-identical to the uninstrumented program.
        # Field-fault injection reads self._field_faults — set by
        # __init__/_rebuild_chunk, not taken here (see ns2d).
        # te_arg=True makes the end time a traced trailing argument (the
        # fleet's per-lane te carry — see models/ns2d._build_chunk).
        metrics = _tm.enabled()
        self._metrics = metrics
        from ..utils.dispatch import resolve_chunk_fuse

        chunk = self.param.tpu_chunk or self.CHUNK
        kfuse = resolve_chunk_fuse(self.param, "ns3d_chunk_fuse", chunk)
        fused = self._build_fused_chunk(backend, metrics=metrics,
                                        te_arg=te_arg, kfuse=kfuse)
        self._fused = fused is not None
        if fused is not None:
            return fused
        step = self._build_step(backend, instrumented=metrics)
        te_static = self.param.te

        def chunk_fn(u, v, w, p, t, nt, *te_in):
            te = te_in[0] if te_in else te_static

            def cond(c):
                return jnp.logical_and(c[4] <= te, c[6] < chunk)

            if kfuse > 1:
                # K-step fused trips (ISSUE 17): one scan advances K
                # gated steps (frozen identity past te) per while trip
                def kblock(c, _):
                    def live(c):
                        return step(*c)

                    return lax.cond(c[4] <= te, live, lambda c: c, c), None

                def body(c):
                    u, v, w, p, t, nt, k = c
                    (u, v, w, p, t, nt), _ = lax.scan(
                        kblock, (u, v, w, p, t, nt), None, length=kfuse)
                    return u, v, w, p, t, nt, k + kfuse
            else:
                def body(c):
                    u, v, w, p, t, nt, k = c
                    u, v, w, p, t, nt = step(u, v, w, p, t, nt)
                    return u, v, w, p, t, nt, k + 1

            u, v, w, p, t, nt, _ = lax.while_loop(
                cond, body, (u, v, w, p, t, nt, jnp.asarray(0, jnp.int32))
            )
            return u, v, w, p, t, nt

        def chunk_fn_metrics(u, v, w, p, t, nt, m, *te_in):
            te = te_in[0] if te_in else te_static

            def cond(c):
                return jnp.logical_and(c[4] <= te, c[6] < chunk)

            if kfuse > 1:
                # per-step metrics_step (POST-step nt) inside the live
                # branch — divergence keeps step resolution in the K-block
                def kblock(c, _):
                    def live(c):
                        (u, v, w, p, t, nt,
                         res, it, dtv, um, vm, wm, bad) = c
                        u, v, w, p, t, nt, res, it, dtv = step(
                            u, v, w, p, t, nt)
                        res, it, dtv, um, vm, wm, bad = _tm.metrics_step(
                            bad, nt, res, it, dtv, ops.max_element(u),
                            ops.max_element(v), ops.max_element(w))
                        return (u, v, w, p, t, nt,
                                res, it, dtv, um, vm, wm, bad)

                    return lax.cond(c[4] <= te, live, lambda c: c, c), None

                def body(c):
                    u, v, w, p, t, nt, k, res, it, dtv, um, vm, wm, bad = c
                    (u, v, w, p, t, nt,
                     res, it, dtv, um, vm, wm, bad), _ = lax.scan(
                        kblock,
                        (u, v, w, p, t, nt, res, it, dtv, um, vm, wm, bad),
                        None, length=kfuse)
                    return (u, v, w, p, t, nt, k + kfuse,
                            res, it, dtv, um, vm, wm, bad)
            else:
                def body(c):
                    u, v, w, p, t, nt, k, res, it, dtv, um, vm, wm, bad = c
                    u, v, w, p, t, nt, res, it, dtv = step(
                        u, v, w, p, t, nt)
                    res, it, dtv, um, vm, wm, bad = _tm.metrics_step(
                        bad, nt, res, it, dtv, ops.max_element(u),
                        ops.max_element(v), ops.max_element(w))
                    return (u, v, w, p, t, nt, k + 1,
                            res, it, dtv, um, vm, wm, bad)

            (u, v, w, p, t, nt, _k,
             res, it, dtv, um, vm, wm, bad) = lax.while_loop(
                cond, body,
                (u, v, w, p, t, nt, jnp.asarray(0, jnp.int32),
                 m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT],
                 m[_tm.M_UMAX], m[_tm.M_VMAX], m[_tm.M_WMAX],
                 m[_tm.M_BAD]),
            )
            return u, v, w, p, t, nt, _tm.metrics_pack(
                res, it, dtv, um, vm, wm, bad)

        return chunk_fn_metrics if metrics else chunk_fn

    def _rebuild_chunk(self):
        """Re-trace the chunk against the solver's CURRENT attributes
        (backend, recovery dt clamp) — the rollback-recovery rebuild hook
        (models/_driver.RingRecovery). Advances the fault-injection
        generation (see models/ns2d._rebuild_chunk)."""
        self._field_faults = _fi.take_field_faults()
        self._chunk_fn = jax.jit(self._build_chunk(backend=self._backend))
        return self._chunk_fn

    def initial_state(self) -> tuple:
        """(u, v, w, p, t, nt[, metrics]) matching the built chunk's arity
        (the NS-2D convention — see models/ns2d.initial_state)."""
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        state = (self.u, self.v, self.w, self.p,
                 jnp.asarray(self.t, time_dtype),
                 jnp.asarray(self.nt, jnp.int32))
        if getattr(self, "_metrics", False):
            state = state + (_tm.metrics_init(),)
        return state

    # -- elastic-checkpoint contract (utils/checkpoint.save_elastic) ---
    def global_shape(self) -> tuple:
        g = self.grid
        return (g.kmax + 2, g.jmax + 2, g.imax + 2)

    def global_fields(self) -> dict:
        """Reference-layout global fields (see models/ns2d.global_fields)."""
        return {f: np.asarray(getattr(self, f))
                for f in ("u", "v", "w", "p")}

    def set_global_fields(self, fields: dict) -> None:
        for f, arr in fields.items():
            cur = getattr(self, f)
            setattr(self, f, jnp.asarray(arr, cur.dtype))

    def run(self, progress: bool = True, on_sync=None) -> None:
        bar = Progress(self.param.te, enabled=progress and not _flags.verbose())
        from ._driver import (
            coord_ckpt_cadence,
            drive_chunks,
            make_recovery,
            pallas_retry,
        )

        state = self.initial_state()
        rec = _tm.ChunkRecorder("ns3d", self.nt) if self._metrics else None
        recover = make_recovery(self, "ns3d", time_index=4, recorder=rec)

        def publish(s):
            self.u, self.v, self.w, self.p = s[0], s[1], s[2], s[3]
            self.t, self.nt = float(s[4]), int(s[5])

        def on_state(s):
            if rec is not None:
                rec.update(float(s[4]), int(s[5]), s[6])
            if recover is not None:
                recover.capture(s)
            if on_sync is not None:
                publish(s)
                on_sync(self)

        if recover is not None:
            recover.capture(state)  # first-chunk divergence is recoverable
        from ..parallel.coordinator import make_coordinator
        from ..utils import xprof as _xprof

        # uncoordinated by default; tpu_coord on = the 1-rank protocol
        # path (see models/ns2d.run)
        coord = make_coordinator(self.param, "ns3d")
        ckpt_every, on_ckpt = coord_ckpt_cadence(self, coord, publish)
        nt0 = self.nt
        with _xprof.capture("ns3d", steps=lambda: self.nt - nt0):
            state = drive_chunks(
                state, self._chunk_fn, self.param.te, 4, bar,
                pallas_retry(
                    self, "3-D pressure solve",
                    restore_after=self.param.tpu_retry_replenish,
                ),
                on_state, lookahead=self.param.tpu_lookahead,
                replenish_after=self.param.tpu_retry_replenish,
                recover=recover, coordinator=coord,
                ckpt_every=ckpt_every, on_ckpt=on_ckpt, family="ns3d",
                ledger=getattr(self, "_fault_ledger", None))
            publish(state)

    def collect(self):
        """Cell-centered global fields (≙ commCollectResult's non-MPI path,
        comm.c:386-426): p interior; velocities averaged from staggered faces."""
        u = np.asarray(self.u)
        v = np.asarray(self.v)
        w = np.asarray(self.w)
        p = np.asarray(self.p)
        pg = p[1:-1, 1:-1, 1:-1]
        ug = (u[1:-1, 1:-1, 1:-1] + u[1:-1, 1:-1, :-2]) / 2.0
        vg = (v[1:-1, 1:-1, 1:-1] + v[1:-1, :-2, 1:-1]) / 2.0
        wg = (w[1:-1, 1:-1, 1:-1] + w[:-2, 1:-1, 1:-1]) / 2.0
        return ug, vg, wg, pg

    def write_result(self, path=None, fmt: str = "ascii") -> None:
        write_vtk_result(self.param, self.grid, self.collect(), path, fmt)
