"""Per-shard Pallas kernel for the DISTRIBUTED quarter-layout red-black SOR.

The production multi-chip hot kernel (≙ the reference's per-rank SOR kernel,
assignment-5/ex5-nazifkar/src/solver.c:586-655): the temporal-blocked quarter
kernel of ops/sor_pallas.make_rb_iter_tblock_quarters, generalized to a shard
of a ("j","i") mesh — masks come from GLOBAL quarter coordinates via two
scalar-prefetch offsets (qoff_j, qoff_i) instead of static bounds, updates
are clipped to the shard's stored logical region, and the residual counts
OWNED cells only (ghost cells are redundantly recomputed by both neighbours
— parallel/quarters_dist.py has the layout derivation and the jnp twin this
kernel must match bitwise in interpret mode).

One call performs g.n red-black iterations (+ the globally-gated Neumann
wall refresh between iterations) in a single HBM sweep — exactly the
validity a depth-n q_exchange provides, so the distributed convergence loop
is: exchange, kernel, psum(residual), repeat.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..parallel.quarters_dist import QGeom, SLOT_PARITY
from .sor_pallas import (
    CompilerParams,
    VMEM_LIMIT_BYTES,
    _check_dtype,
    pltpu,
    quarters_feasible,
    quarters_vmem_bytes,
)


def _qdist_kernel(
    sref,   # SMEM scalar prefetch: int32[2] = (qoff_j, qoff_i)
    p_in,   # ANY (4, rp, w2p) stacked stored plane [R0, R1, B0, B1]
    rhs,    # ANY (4, rp, w2p)
    p_out,  # ANY (4, rp, w2p)
    res,    # SMEM (1, 1) owned-residual accumulator
    pw2,    # VMEM (2, 4, brq+2h, w2p) double-buffered p windows
    rw2,    # VMEM (2, 4, brq+2h, w2p)
    ob2,    # VMEM (2, 4, brq, w2p) out bands
    vacc,   # VMEM (1, w2p) per-lane residual accumulator
    ld_sem,  # DMA (2, 8)
    st_sem,  # DMA (2, 4)
    *,
    g: QGeom,
    factor: float,
    idx2: float,
    idy2: float,
):
    b = pl.program_id(0)
    brq = g.brq
    h = g.h
    slot = b % 2
    nslot = (b + 1) % 2
    qoff_j = sref[0]
    qoff_i = sref[1]

    def load(k, s):
        copies = []
        for qi in range(4):
            copies.append(pltpu.make_async_copy(
                p_in.at[qi, pl.ds(k * brq, brq + 2 * h), :],
                pw2.at[s, qi], ld_sem.at[s, qi]))
            copies.append(pltpu.make_async_copy(
                rhs.at[qi, pl.ds(k * brq, brq + 2 * h), :],
                rw2.at[s, qi], ld_sem.at[s, 4 + qi]))
        return copies

    def store(k, s):
        return [pltpu.make_async_copy(
            ob2.at[s, qi], p_out.at[qi, pl.ds(h + k * brq, brq), :],
            st_sem.at[s, qi]) for qi in range(4)]

    @pl.when(b == 0)
    def _():
        res[0, 0] = jnp.zeros((), p_out.dtype)
        vacc[...] = jnp.zeros_like(vacc)
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < g.nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    R0, R1, B0, B1 = (pw2[slot, qi] for qi in range(4))
    F0, F1, G0, G1 = (rw2[slot, qi] for qi in range(4))

    # stored row of window cell (w, c): rho = b*brq + w; logical lam = rho-h;
    # global quarter coords gqr = lam - n + qoff_j, gqc = c - n + qoff_i
    # (parallel/quarters_dist.q_masks — keep the formulas in lockstep)
    rho = b * brq + jax.lax.broadcasted_iota(jnp.int32, R0.shape, 0)
    ccol = jax.lax.broadcasted_iota(jnp.int32, R0.shape, 1)
    lam = rho - h
    gqr = lam - g.n + qoff_j
    gqc = ccol - g.n + qoff_i
    valid = (lam >= 0) & (lam < g.jq) & (ccol >= 0) & (ccol < g.iq)
    # freeze the outermost stored ring (parallel/quarters_dist.q_masks)
    valid_upd = (
        (lam >= 1) & (lam <= g.jq - 2) & (ccol >= 1) & (ccol <= g.iq - 2)
    )

    def row_int(pr):
        if pr == 0:
            return (gqr >= 1) & (gqr <= g.jmax // 2)
        return (gqr >= 0) & (gqr <= g.jmax // 2 - 1)

    def col_int(pc):
        if pc == 0:
            return (gqc >= 1) & (gqc <= g.imax // 2)
        return (gqc >= 0) & (gqc <= g.imax // 2 - 1)

    m_upd = [row_int(pr) & col_int(pc) & valid_upd for pr, pc in SLOT_PARITY]
    row_lo_pc0 = (gqr == 0) & col_int(0) & valid
    row_lo_pc1 = (gqr == 0) & col_int(1) & valid
    row_hi_pc0 = (gqr == g.jmax // 2) & col_int(0) & valid
    row_hi_pc1 = (gqr == g.jmax // 2) & col_int(1) & valid
    col_lo_pr0 = (gqc == 0) & row_int(0) & valid
    col_lo_pr1 = (gqc == 0) & row_int(1) & valid
    col_hi_pr0 = (gqc == g.imax // 2) & row_int(0) & valid
    col_hi_pr1 = (gqc == g.imax // 2) & row_int(1) & valid
    # owned region (residual accounting; static layout bounds)
    own = []
    for pr, pc in SLOT_PARITY:
        osr = g.row_base + (1 if pr == 0 else 0)
        osc = g.col_base + (1 if pc == 0 else 0)
        own.append(
            (rho >= osr) & (rho < osr + g.jl // 2)
            & (ccol >= osc) & (ccol < osc + g.il // 2)
        )

    def upd(center, rhs_q, w, e, s, n_, mask):
        r = rhs_q - ((e - 2.0 * center + w) * idx2
                     + (n_ - 2.0 * center + s) * idy2)
        rm = jnp.where(mask, r, jnp.zeros_like(r))
        return center - factor * rm, rm

    def east(x):
        return jnp.roll(x, -1, axis=1)

    def west(x):
        return jnp.roll(x, 1, axis=1)

    def north(x):
        return jnp.roll(x, -1, axis=0)

    def south(x):
        return jnp.roll(x, 1, axis=0)

    r0 = r1 = r2 = r3 = None
    for _ in range(g.n):
        R0, r0 = upd(R0, F0, west(B0), B0, south(B1), B1, m_upd[0])
        R1, r1 = upd(R1, F1, B1, east(B1), B0, north(B0), m_upd[1])
        B0, r2 = upd(B0, G0, R0, east(R0), south(R1), R1, m_upd[2])
        B1, r3 = upd(B1, G1, west(R1), R1, R0, north(R0), m_upd[3])
        R0 = jnp.where(row_lo_pc0, B1, R0)
        B0 = jnp.where(row_lo_pc1, R1, B0)
        R1 = jnp.where(row_hi_pc1, B0, R1)
        B1 = jnp.where(row_hi_pc0, R0, B1)
        R0 = jnp.where(col_lo_pr0, B0, R0)
        B1 = jnp.where(col_lo_pr1, R1, B1)
        B0 = jnp.where(col_hi_pr0, R0, B0)
        R1 = jnp.where(col_hi_pr1, B1, R1)

    @pl.when(b >= 2)
    def _():
        for c in store(b - 2, slot):
            c.wait()

    for qi, arr in enumerate((R0, R1, B0, B1)):
        ob2[slot, qi] = arr[h: h + brq, :]
    for c in store(b, slot):
        c.start()

    # residual of the final iteration, OWNED cells only (ghosts are the
    # neighbours' cells; where-select so ghost garbage can't poison via 0·inf)
    acc = jnp.zeros_like(vacc[...])
    for rq, ow in zip((r0, r1, r2, r3), own):
        rq_own = jnp.where(ow, rq * rq, jnp.zeros_like(rq))
        acc = acc + jnp.sum(rq_own[h: h + brq, :], axis=0, keepdims=True)
    vacc[...] += acc

    @pl.when(b == g.nblocks - 1)
    def _():
        res[0, 0] += jnp.sum(vacc[...])

    @pl.when(b == g.nblocks - 1)
    def _():
        for c in store(b, slot):
            c.wait()
        if g.nblocks > 1:
            for c in store(b - 1, nslot):
                c.wait()


def make_rb_iters_qdist(g: QGeom, dx: float, dy: float, omega: float, dtype,
                        *, interpret: bool | None = None):
    """Build `(qoffs_i32[2], p_stacked, rhs_stacked) ->
    (p_stacked', owned res sum of last iter)` performing g.n red-black
    iterations on the (4, rp, w2p) stored plane of parallel/quarters_dist.
    Call INSIDE shard_map with qoffs = [joff//2, ioff//2]."""
    if pltpu is None:
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)
    itemsize = jnp.dtype(dtype).itemsize
    if not quarters_feasible(g.brq, g.h, g.w2p, itemsize):
        raise ValueError(
            f"quarters-dist scratch {quarters_vmem_bytes(g.brq, g.h, g.w2p, itemsize) >> 20} MiB "
            f"exceeds the VMEM budget (brq={g.brq}, h={g.h}, w2p={g.w2p}); "
            "reduce tpu_ca_inner or the per-shard width"
        )

    dx2, dy2 = dx * dx, dy * dy
    kernel = functools.partial(
        _qdist_kernel,
        g=g,
        factor=omega * 0.5 * (dx2 * dy2) / (dx2 + dy2),
        idx2=1.0 / dx2,
        idy2=1.0 / dy2,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g.nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, 4, g.brq + 2 * g.h, g.w2p), dtype),
            pltpu.VMEM((2, 4, g.brq + 2 * g.h, g.w2p), dtype),
            pltpu.VMEM((2, 4, g.brq, g.w2p), dtype),
            pltpu.VMEM((1, g.w2p), dtype),
            pltpu.SemaphoreType.DMA((2, 8)),
            pltpu.SemaphoreType.DMA((2, 4)),
        ],
    )
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((4, g.rp, g.w2p), dtype),
            jax.ShapeDtypeStruct((1, 1), dtype),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )

    def rb_iters(qoffs, p_stacked, rhs_stacked):
        p_stacked, res = call(qoffs, p_stacked, rhs_stacked)
        return p_stacked, res[0, 0]

    return rb_iters
