"""Per-shard Pallas kernel for the DISTRIBUTED 3-D flag-masked (obstacle)
SOR — the 3-D companion of ops/sor_obsdist.py, completing the
kernel-per-shard family over every distributed pressure-solve surface
(quarters 2-D, octants 3-D, masked 2-D, masked 3-D).

The masked mode of sor3d_pallas._tblock3d_kernel generalized to a shard of
a ("k","j","i") mesh: global-coordinate masks via three scalar-prefetch
offsets, frozen outermost stored ring, owned-only residual, per-direction
fluid coefficients from the shard's deep flag block (shared math:
sor3d_pallas.masked_stencil_ops_3d / rb_inner_sweeps_3d). jnp twin:
ops/obstacle3d.ca_rb_iters_obstacle_3d.

Layout: the (kl+2H, jl+2H, il+2H) deep-halo extended block (H = 2n) in
sor3d_pallas's padded layout (pad_array_3d; block axis k, window halo
h = 2n planes). Cell (a, b, c) holds global extended index
(a - H + koff + 1, b - H + joff + 1, c - H + ioff + 1)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .sor3d_pallas import (
    VMEM_LIMIT_BYTES,
    CompilerParams,
    _check_dtype,
    masked_stencil_ops_3d,
    padded_ji,
    pick_block_k,
    pltpu,
    rb_inner_sweeps_3d,
    tblock3d_halo,
)


def _obsdist3d_kernel(
    sref,   # SMEM scalar prefetch: int32[3] = (koff, joff, ioff)
    p_in, rhs, flg, p_out, res,
    pw2, rw2, fw2, ob2, vacc, ld_sem, st_sem,
    *,
    n_inner: int,
    block_k: int,
    nblocks: int,
    gkmax: int, gjmax: int, gimax: int,
    kl: int, jl: int, il: int,
    H: int,
    halo: int,
    omega: float,
    idx2: float, idy2: float, idz2: float,
):
    b = pl.program_id(0)
    bk = block_k
    h = halo
    slot = b % 2
    nslot = (b + 1) % 2
    koff, joff, ioff = sref[0], sref[1], sref[2]

    def load(k, s):
        return [
            pltpu.make_async_copy(
                p_in.at[pl.ds(k * bk, bk + 2 * h)], pw2.at[s],
                ld_sem.at[s, 0]),
            pltpu.make_async_copy(
                rhs.at[pl.ds(k * bk, bk + 2 * h)], rw2.at[s],
                ld_sem.at[s, 1]),
            pltpu.make_async_copy(
                flg.at[pl.ds(k * bk, bk + 2 * h)], fw2.at[s],
                ld_sem.at[s, 2]),
        ]

    def store(k, s):
        return pltpu.make_async_copy(
            ob2.at[s], p_out.at[pl.ds(h + k * bk, bk)], st_sem.at[s]
        )

    @pl.when(b == 0)
    def _():
        res[0, 0] = jnp.zeros((), res.dtype)
        vacc[...] = jnp.zeros_like(vacc)
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    p = pw2[slot]
    rw = rw2[slot]
    fl = fw2[slot]

    # padded plane of window cell (wk, wj, wi): s = b*bk + wk; local deep
    # index a_k = s - h; global extended gk = a_k - H + koff + 1 (j/i have
    # no kernel padding offset: a_j = wj, a_i = wi)
    s_k = b * bk + jax.lax.broadcasted_iota(jnp.int32, p.shape, 0)
    a_k = s_k - h
    a_j = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
    a_i = jax.lax.broadcasted_iota(jnp.int32, p.shape, 2)
    gk = a_k - H + koff + 1
    gj = a_j - H + joff + 1
    gi = a_i - H + ioff + 1
    interior = (
        (gk >= 1) & (gk <= gkmax)
        & (gj >= 1) & (gj <= gjmax)
        & (gi >= 1) & (gi <= gimax)
    )
    valid_upd = (
        (a_k >= 1) & (a_k <= kl + 2 * H - 2)
        & (a_j >= 1) & (a_j <= jl + 2 * H - 2)
        & (a_i >= 1) & (a_i <= il + 2 * H - 2)
    )
    fluid = fl != 0
    par = (gi + gj + gk) % 2
    odd = interior & (par == 1) & fluid & valid_upd
    even = interior & (par == 0) & fluid & valid_upd
    tan_ji = (gj >= 1) & (gj <= gjmax) & (gi >= 1) & (gi <= gimax)
    tan_ki = (gk >= 1) & (gk <= gkmax) & (gi >= 1) & (gi <= gimax)
    tan_kj = (gk >= 1) & (gk <= gkmax) & (gj >= 1) & (gj <= gjmax)
    front = (gk == 0) & tan_ji & valid_upd
    back = (gk == gkmax + 1) & tan_ji & valid_upd
    bottom = (gj == 0) & tan_ki & valid_upd
    top = (gj == gjmax + 1) & tan_ki & valid_upd
    left = (gi == 0) & tan_kj & valid_upd
    right = (gi == gimax + 1) & tan_kj & valid_upd
    owned = (
        (a_k >= H) & (a_k < H + kl)
        & (a_j >= H) & (a_j < H + jl)
        & (a_i >= H) & (a_i < H + il)
    )

    fac, lap = masked_stencil_ops_3d(fl, idx2, idy2, idz2, omega)
    p, r_odd, r_evn = rb_inner_sweeps_3d(
        p, rw, n_inner, odd, even, fac, lap,
        (front, back, bottom, top, left, right),
    )

    @pl.when(b >= 2)
    def _():
        store(b - 2, slot).wait()

    ob2[slot] = p[h: h + bk]
    store(b, slot).start()

    ro = jnp.where(owned, r_odd * r_odd + r_evn * r_evn, 0.0)
    vacc[...] += jnp.sum(ro[h: h + bk], axis=(0, 1))[None, :]

    @pl.when(b == nblocks - 1)
    def _():
        res[0, 0] += jnp.sum(vacc[...])
        store(b, slot).wait()
        if nblocks > 1:
            store(b - 1, nslot).wait()


def make_rb_iters_obsdist_3d(kmax, jmax, imax, kl, jl, il, n, dx, dy, dz,
                             omega, dtype, *,
                             interpret: bool | None = None,
                             block_k: int | None = None):
    """Build `(offs_i32[3], p_padded, rhs_padded, flg_padded) ->
    (p_padded', owned res sum of last iter)` performing n 3-D red-black
    eps-coefficient iterations on the padded (kl+2H, jl+2H, il+2H) deep
    block (pad with sor3d_pallas.pad_array_3d(x, block_k, n)). Returns
    (rb_iters, block_k). offs = [koff, joff, ioff] grid offsets."""
    if pltpu is None:
        return None, 0
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)
    H = 2 * n
    ext_k, ext_j, ext_i = kl + 2 * H, jl + 2 * H, il + 2 * H
    h = tblock3d_halo(n)
    if block_k is None:
        block_k = pick_block_k(ext_k - 2, ext_j - 2, ext_i - 2, dtype, n,
                               masked=True)
    jp, ip = padded_ji(ext_j - 2, ext_i - 2, dtype)
    plane = jp * ip * jnp.dtype(dtype).itemsize
    # masked resident planes: 15*bk + 18*h (pick_block_k's accounting)
    if (15 * block_k + 18 * h) * plane > VMEM_LIMIT_BYTES // 2:
        raise ValueError(
            f"obstacle-dist-3d scratch exceeds the VMEM budget "
            f"(block_k={block_k}, h={h}, plane={jp}x{ip}); reduce "
            "tpu_ca_inner or the shard size"
        )
    from .sor3d_pallas import block_k_degenerate

    if block_k_degenerate(block_k, ext_k - 2, n):
        # the budget (not the grid) forced block_k below the halo depth:
        # >3x redundant halo recompute per grid step — the dispatcher
        # should take the jnp CA path instead of a pathological kernel
        raise ValueError(
            f"obstacle-dist-3d block_k={block_k} degenerate below halo "
            f"h={h} on this shard plane ({jp}x{ip}); jnp path is faster"
        )
    nblocks = -(-ext_k // block_k)
    kp = nblocks * block_k + 2 * h
    kernel = functools.partial(
        _obsdist3d_kernel,
        n_inner=n, block_k=block_k, nblocks=nblocks,
        gkmax=kmax, gjmax=jmax, gimax=imax,
        kl=kl, jl=jl, il=il, H=H, halo=h, omega=omega,
        idx2=1.0 / (dx * dx), idy2=1.0 / (dy * dy), idz2=1.0 / (dz * dz),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype),
            pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype),
            pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype),
            pltpu.VMEM((2, block_k, jp, ip), dtype),
            pltpu.VMEM((1, ip), dtype),
            pltpu.SemaphoreType.DMA((2, 3)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((kp, jp, ip), dtype),
            jax.ShapeDtypeStruct((1, 1), dtype),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )

    def rb_iters(offs, p_padded, rhs_padded, flg_padded):
        p_padded, r = call(offs, p_padded, rhs_padded, flg_padded)
        return p_padded, r[0, 0]

    return rb_iters, block_k


def padded_deep_exchange_3d(xp, comm, H, k0, ext_k, ext_j, ext_i):
    """halo_exchange(depth=H) on the PADDED 3-D layout (pad_array_3d):
    logical k-planes at [k0, k0+ext_k), j at [0, ext_j), i at [0, ext_i) —
    the 3-D twin of sor_obsdist.padded_deep_exchange."""
    from jax import lax

    from ..parallel.comm import _nbr_perm

    for axis, name, off, ext in (
        (0, "k", k0, ext_k), (1, "j", 0, ext_j), (2, "i", 0, ext_i)
    ):
        nper = comm.axis_size(name)
        if nper == 1:
            continue
        idx = lax.axis_index(name)
        lo_g, hi_g = off, off + ext - H
        lo_o, hi_o = off + H, off + ext - 2 * H

        def sl(start):
            return lax.slice_in_dim(xp, start, start + H, axis=axis)

        from_lo = lax.ppermute(sl(hi_o), name, _nbr_perm(nper, True, False))
        from_hi = lax.ppermute(sl(lo_o), name, _nbr_perm(nper, False, False))
        from_lo = jnp.where(idx > 0, from_lo, sl(lo_g))
        from_hi = jnp.where(idx < nper - 1, from_hi, sl(hi_g))
        xp = lax.dynamic_update_slice_in_dim(xp, from_lo, lo_g, axis=axis)
        xp = lax.dynamic_update_slice_in_dim(xp, from_hi, hi_g, axis=axis)
    return xp
