"""3-D red-black SOR as a Pallas TPU kernel — the NS-3D pressure-solve hot op.

Capability parity: the reference's 3-D red-black pressure solve
(/root/reference/assignment-6/src/solver.c: solve:175-297 — the ksw/jsw/isw
checkerboard, 7-point stencil, 6-face Neumann ghost refresh), re-designed for
the TPU memory hierarchy exactly like the 2-D kernel (`ops/sor_pallas.py`):

- One `pallas_call` performs `n_inner` FULL red-black iterations (odd
  half-sweep, even half-sweep, 6-face Neumann refresh) plus the residual of
  the last iteration, in a single HBM sweep — temporal blocking over k-plane
  blocks. The jnp path (`models/ns3d.sor_pass_3d`) streams p and rhs through
  HBM twice per iteration.
- The block axis is k, the MAJOR array axis: a window slices whole (j, i)
  planes, and leading-axis DMA slices carry no tile-alignment constraint
  (tiles live on the minor two axes), so no sublane rounding of the block
  size is needed — only j (sublane) and i (lane) are padded.
- Halo arithmetic is identical to the 2-D kernel, one dimension up: one RB
  iteration consumes 2 planes of window validity (odd reads ±1 plane, even
  reads odd-updated values ±1 plane), so `halo = 2·n_inner` planes on each
  side of the owned block yield a fully-valid owned block with no second HBM
  pass. Halo planes are recomputed redundantly by both neighbouring blocks
  (same data, same arithmetic — identical values).
- The checkerboard is branch-free: parity mask (i+j+k) % 2 from
  `broadcasted_iota` on GLOBAL logical coordinates; pass 0 visits odd parity,
  pass 1 even — the reference's sweep order (isw/jsw/ksw stride-2 loops).
- The 6-face Neumann refresh runs INSIDE the sweep between iterations (mask
  form of `models/ns3d.neumann_faces_3d`: faces only, tangentially clipped to
  the interior, edges/corners and dead padding untouched).
- Residual: accumulated for the LAST iteration only over the owned block,
  reduced along k and sublanes into a per-lane vector accumulator; the
  cross-lane reduction happens once in the final grid step (measured ~25%
  of kernel time when done per block in the 2-D kernel).

Layout: logical arrays are (kmax+2, jmax+2, imax+2), [k, j, i], i minor.
Padded shape: (nblocks·block_k + 2·halo, sublane_round(jmax+2),
lane_round(imax+2)); dead cells are zero on entry and never written.
`pad_array_3d`/`unpad_array_3d` convert at the convergence-loop boundary
only — the loop carries the padded array.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from .sor_pallas import CompilerParams, LANE, VMEM_LIMIT_BYTES, _align, _check_dtype


def padded_ji(jmax: int, imax: int, dtype) -> tuple[int, int]:
    """In-plane padded shape: j+2 to the sublane tile, i+2 to the lane tile."""
    a = _align(dtype)
    jp = -(-(jmax + 2) // a) * a
    ip = -(-(imax + 2) // LANE) * LANE
    return jp, ip


def tblock3d_halo(n_inner: int) -> int:
    """Window halo in planes: 2 per fused iteration; the k axis is untiled so
    no alignment rounding applies."""
    return 2 * n_inner


def _neighbours3(x):
    return (
        jnp.roll(x, -1, axis=2), jnp.roll(x, 1, axis=2),   # east, west
        jnp.roll(x, -1, axis=1), jnp.roll(x, 1, axis=1),   # north, south
        jnp.roll(x, -1, axis=0), jnp.roll(x, 1, axis=0),   # back, front
    )


def masked_stencil_ops_3d(fl, idx2, idy2, idz2, omega):
    """(fac, lap) for the 3-D flag-masked (obstacle) stencil — the single
    home of the eps-coefficient kernel math, shared by _tblock3d_kernel's
    masked mode and the distributed ops/sor_obsdist3d kernel (same
    discipline as sor_pallas.masked_stencil_ops). Arithmetic matches
    ops/obstacle3d.sor_pass_obstacle_3d."""
    eps_e, eps_w, eps_n, eps_s, eps_b, eps_f = _neighbours3(fl)
    denom = ((eps_e + eps_w) * idx2 + (eps_n + eps_s) * idy2
             + (eps_b + eps_f) * idz2)
    fac = jnp.where(denom > 0, omega / denom, 0.0) * fl

    def lap(x):
        east, west, north, south, back_, frnt = _neighbours3(x)
        return (
            (eps_e * (east - x) + eps_w * (west - x)) * idx2
            + (eps_n * (north - x) + eps_s * (south - x)) * idy2
            + (eps_b * (back_ - x) + eps_f * (frnt - x)) * idz2
        )

    return fac, lap


def rb_inner_sweeps_3d(p, rw, n_inner, odd, even, fac, lap, faces):
    """The fused 3-D red-black inner loop (ODD parity first — the
    reference's sweep order) + per-iteration 6-face Neumann refresh, shared
    by _tblock3d_kernel and the distributed obstacle kernel. `faces` =
    (front, back, bottom, top, left, right) select masks. Returns
    (p, r_odd, r_evn) of the LAST iteration."""
    front, back, bottom, top, left, right = faces
    r_odd = r_evn = None
    for _t in range(n_inner):
        r_odd = jnp.where(odd, rw - lap(p), 0.0)
        p = p - fac * r_odd
        r_evn = jnp.where(even, rw - lap(p), 0.0)
        p = p - fac * r_evn
        p = jnp.where(front, jnp.roll(p, -1, axis=0), p)
        p = jnp.where(back, jnp.roll(p, 1, axis=0), p)
        p = jnp.where(bottom, jnp.roll(p, -1, axis=1), p)
        p = jnp.where(top, jnp.roll(p, 1, axis=1), p)
        p = jnp.where(left, jnp.roll(p, -1, axis=2), p)
        p = jnp.where(right, jnp.roll(p, 1, axis=2), p)
    return p, r_odd, r_evn


def pick_block_k(kmax: int, jmax: int, imax: int, dtype=jnp.float32,
                 n_inner: int = 1, masked: bool = False) -> int:
    """Block depth (planes per grid step). The kernel's resident planes are
    2·(bk+2h) window + 2·bk store buffers = 6·bk + 8·h; budget them against
    ~half the raised VMEM limit (Mosaic temporaries take the rest), capped by
    the whole grid and a per-step-overhead floor.

    masked adds a third double-buffered flag window (+2·(bk+2h) planes) AND
    seven flag-derived full-window temporaries (eps_e..eps_f, fac) live
    across the inner loop — budget 15·bk + 18·h resident planes there."""
    jp, ip = padded_ji(jmax, imax, dtype)
    plane = jp * ip * jnp.dtype(dtype).itemsize
    h = tblock3d_halo(n_inner)
    # ~4 MiB per window buffer measured fastest at 128³ on v5e (larger blocks
    # add VMEM pressure, smaller ones pay more per-grid-step overhead) ...
    bk = (4 << 20) // plane - 2 * h
    # ... clamped to what the resident planes can actually hold
    per_bk, per_h = (15, 18) if masked else (6, 8)
    feasible = ((VMEM_LIMIT_BYTES // 2) // plane - per_h * h) // per_bk
    return max(1, min(bk, feasible, kmax + 2, 64))


def block_k_degenerate(block_k: int, kmax: int, n_inner: int) -> bool:
    """True when the budget (not the grid) forced block_k below the halo
    depth — the redundant halo recompute then exceeds ~3x and VMEM likely
    can't hold the windows; the dispatcher should use the jnp path instead
    of a pathological kernel."""
    h = tblock3d_halo(n_inner)
    return block_k < h and block_k < kmax + 2


def padded_k(kmax: int, block_k: int, n_inner: int = 1) -> int:
    nblocks = -(-(kmax + 2) // block_k)
    return nblocks * block_k + 2 * tblock3d_halo(n_inner)


def pad_array_3d(x, block_k: int, n_inner: int = 1):
    """(kmax+2, jmax+2, imax+2) -> padded layout, dead cells zero."""
    kmax = x.shape[0] - 2
    jp, ip = padded_ji(x.shape[1] - 2, x.shape[2] - 2, x.dtype)
    kp = padded_k(kmax, block_k, n_inner)
    h = tblock3d_halo(n_inner)
    out = jnp.zeros((kp, jp, ip), x.dtype)
    return out.at[h : h + kmax + 2, : x.shape[1], : x.shape[2]].set(x)


def unpad_array_3d(xp, kmax: int, jmax: int, imax: int, n_inner: int = 1):
    h = tblock3d_halo(n_inner)
    return xp[h : h + kmax + 2, : jmax + 2, : imax + 2]


def _tblock3d_kernel(
    *refs,  # see unpacking below: [p_in, rhs(, flg)] + [p_out, res] + scratch
    n_inner: int,
    block_k: int,
    nblocks: int,
    kmax: int,
    jmax: int,
    imax: int,
    halo: int,
    factor: float,
    omega: float,
    idx2: float,
    idy2: float,
    idz2: float,
    masked: bool,
):
    """masked=True adds a fluid-flag input (ops/obstacle3d.py flag field,
    padded) and switches the stencil to per-direction fluid coefficients
    with a per-cell relaxation ω/denom — the 3-D form of the 2-D kernel's
    masked mode (_tblock_kernel); arithmetic matches
    ops/obstacle3d.sor_pass_obstacle_3d term-for-term. Flag-derived
    coefficient arrays are computed once per block, outside the iteration
    loop."""
    if masked:
        (p_in, rhs, flg, p_out, res,
         pw2, rw2, fw2, ob2, vacc, ld_sem, st_sem) = refs
    else:
        (p_in, rhs, p_out, res,
         pw2, rw2, ob2, vacc, ld_sem, st_sem) = refs
        flg = fw2 = None
    b = pl.program_id(0)
    bk = block_k
    h = halo
    slot = b % 2
    nslot = (b + 1) % 2

    def load(k, s):
        copies = [
            pltpu.make_async_copy(
                p_in.at[pl.ds(k * bk, bk + 2 * h)], pw2.at[s], ld_sem.at[s, 0]
            ),
            pltpu.make_async_copy(
                rhs.at[pl.ds(k * bk, bk + 2 * h)], rw2.at[s], ld_sem.at[s, 1]
            ),
        ]
        if masked:
            copies.append(
                pltpu.make_async_copy(
                    flg.at[pl.ds(k * bk, bk + 2 * h)], fw2.at[s],
                    ld_sem.at[s, 2],
                )
            )
        return copies

    def store(k, s):
        return pltpu.make_async_copy(
            ob2.at[s], p_out.at[pl.ds(h + k * bk, bk)], st_sem.at[s]
        )

    @pl.when(b == 0)
    def _():
        res[0, 0] = jnp.zeros((), p_out.dtype)
        vacc[...] = jnp.zeros_like(vacc)
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    p = pw2[slot]
    rw = rw2[slot]

    # logical (k, j, i) of window cell (wk, wj, wi): k = b*bk + wk - h
    kk = b * bk - h + jax.lax.broadcasted_iota(jnp.int32, p.shape, 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
    ii = jax.lax.broadcasted_iota(jnp.int32, p.shape, 2)
    interior = (
        (kk >= 1) & (kk <= kmax)
        & (jj >= 1) & (jj <= jmax)
        & (ii >= 1) & (ii <= imax)
    )
    odd = interior & (((ii + jj + kk) % 2) == 1)
    even = interior & (((ii + jj + kk) % 2) == 0)
    # 6-face Neumann refresh masks, tangentially clipped to the interior
    # (models/ns3d.neumann_faces_3d: [1:-1] tangential ranges)
    tan_ji = (jj >= 1) & (jj <= jmax) & (ii >= 1) & (ii <= imax)
    tan_ki = (kk >= 1) & (kk <= kmax) & (ii >= 1) & (ii <= imax)
    tan_kj = (kk >= 1) & (kk <= kmax) & (jj >= 1) & (jj <= jmax)
    front = (kk == 0) & tan_ji
    back = (kk == kmax + 1) & tan_ji
    bottom = (jj == 0) & tan_ki
    top = (jj == jmax + 1) & tan_ki
    left = (ii == 0) & tan_kj
    right = (ii == imax + 1) & tan_kj

    if masked:
        # per-block constants (flags don't change across inner iterations)
        fl = fw2[slot]
        odd = odd & (fl != 0)
        even = even & (fl != 0)
        fac, lap = masked_stencil_ops_3d(fl, idx2, idy2, idz2, omega)
    else:
        fac = factor

        def lap(x):
            east, west, north, south, back_, frnt = _neighbours3(x)
            return (
                (east - 2.0 * x + west) * idx2
                + (north - 2.0 * x + south) * idy2
                + (back_ - 2.0 * x + frnt) * idz2
            )

    p, r_odd, r_evn = rb_inner_sweeps_3d(
        p, rw, n_inner, odd, even, fac, lap,
        (front, back, bottom, top, left, right),
    )

    @pl.when(b >= 2)
    def _():
        store(b - 2, slot).wait()

    ob2[slot] = p[h : h + bk]
    store(b, slot).start()

    # residual of the final iteration, owned block only; reduce k + sublanes
    # into the per-lane accumulator, cross-lane reduction once at the end
    ro = r_odd[h : h + bk]
    eo = r_evn[h : h + bk]
    vacc[...] += jnp.sum(ro * ro + eo * eo, axis=(0, 1))[None, :]

    @pl.when(b == nblocks - 1)
    def _():
        res[0, 0] += jnp.sum(vacc[...])
        store(b, slot).wait()
        if nblocks > 1:  # static: drain the previous slot's store too
            store(b - 1, nslot).wait()


def make_rb_iter_tblock_3d(
    imax: int,
    jmax: int,
    kmax: int,
    dx: float,
    dy: float,
    dz: float,
    omega: float,
    dtype,
    *,
    n_inner: int = 1,
    block_k: int | None = None,
    interpret: bool | None = None,
    fluid=None,
):
    """Build `(p_padded, rhs_padded) -> (p_padded', res_sumsq_of_last_iter)`
    where one call performs `n_inner` 3-D red-black iterations + Neumann BCs.
    Returns (rb_iter, block_k); pad with `pad_array_3d(x, block_k, n_inner)`.

    fluid: optional (kmax+2, jmax+2, imax+2) 0/1 flag field
    (ops/obstacle3d.py) — switches to the obstacle stencil (per-direction
    fluid coefficients, per-cell factor); the padded flag array is baked
    into the returned closure as a constant.
    """
    if pltpu is None:
        return None, 0
    if block_k is None:
        block_k = pick_block_k(kmax, jmax, imax, dtype, n_inner,
                               masked=fluid is not None)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)

    # lazy: models.ns3d imports this module for backend dispatch
    from ..models.ns3d import sor_coefficients_3d

    factor, idx2, idy2, idz2 = sor_coefficients_3d(dx, dy, dz, omega)
    masked = fluid is not None
    h = tblock3d_halo(n_inner)
    jp, ip = padded_ji(jmax, imax, dtype)
    nblocks = -(-(kmax + 2) // block_k)
    kp = nblocks * block_k + 2 * h
    kernel = functools.partial(
        _tblock3d_kernel,
        n_inner=n_inner,
        block_k=block_k,
        nblocks=nblocks,
        kmax=kmax,
        jmax=jmax,
        imax=imax,
        halo=h,
        factor=factor,
        omega=omega,
        idx2=idx2,
        idy2=idy2,
        idz2=idz2,
        masked=masked,
    )
    n_in = 3 if masked else 2
    scratch = [
        pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype),
        pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype),
    ]
    if masked:
        scratch.append(pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype))
    scratch += [
        pltpu.VMEM((2, block_k, jp, ip), dtype),
        pltpu.VMEM((1, ip), dtype),
        pltpu.SemaphoreType.DMA((2, n_in)),
        pltpu.SemaphoreType.DMA((2,)),
    ]
    call = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * n_in,
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 1), lambda b: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((kp, jp, ip), dtype),
            jax.ShapeDtypeStruct((1, 1), dtype),
        ],
        scratch_shapes=scratch,
        compiler_params=CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )

    if masked:
        flg_padded = pad_array_3d(jnp.asarray(fluid, dtype), block_k, n_inner)

        def rb_iter(p_padded, rhs_padded):
            p_padded, res = call(p_padded, rhs_padded, flg_padded)
            return p_padded, res[0, 0]
    else:

        def rb_iter(p_padded, rhs_padded):
            p_padded, res = call(p_padded, rhs_padded)
            return p_padded, res[0, 0]

    return rb_iter, block_k


def _tblock3d_octants_kernel(
    p_in,   # ANY (8, sp, jp2, ip2) stacked octants, sor_octants.BITS order
    rhs,    # ANY (8, sp, jp2, ip2)
    p_out,  # ANY (8, sp, jp2, ip2)
    res,    # SMEM (1, 1)
    pw2,    # VMEM (16, bk+2h, jp2, ip2): slot*8 + octant (Mosaic wants ≤4-D)
    rw2,    # VMEM (16, bk+2h, jp2, ip2)
    ob2,    # VMEM (16, bk, jp2, ip2)
    vacc,   # VMEM (1, ip2)
    ld_sem,  # DMA (2, 16)
    st_sem,  # DMA (2, 8)
    *,
    n_inner: int,
    block_k: int,  # octant planes per block
    nblocks: int,
    k2: int,  # (kmax+2)//2 etc. — logical octant extents
    j2: int,
    i2: int,
    halo: int,
    factor: float,
    idx2: float,
    idy2: float,
    idz2: float,
):
    """Temporal-blocked 3-D red-black sweep in the OCTANT layout
    (ops/sor_octants.py): every 7-point neighbour a uniform shift, every
    lane productive, the 6-face Neumann refresh 24 same-index plane
    selects. One iteration consumes ONE octant plane of halo per side
    (= 2 grid planes, matching the checkerboard kernel)."""
    from .sor_octants import BITS, EVEN, ODD, _flip

    b = pl.program_id(0)
    bk = block_k
    h = halo
    slot = b % 2
    nslot = (b + 1) % 2
    qidx = {bits: i for i, bits in enumerate(BITS)}

    def load(k, s):
        copies = []
        for qi in range(8):
            copies.append(pltpu.make_async_copy(
                p_in.at[qi, pl.ds(k * bk, bk + 2 * h)], pw2.at[s * 8 + qi],
                ld_sem.at[s, qi]))
            copies.append(pltpu.make_async_copy(
                rhs.at[qi, pl.ds(k * bk, bk + 2 * h)], rw2.at[s * 8 + qi],
                ld_sem.at[s, 8 + qi]))
        return copies

    def store(k, s):
        return [pltpu.make_async_copy(
            ob2.at[s * 8 + qi], p_out.at[qi, pl.ds(h + k * bk, bk)],
            st_sem.at[s, qi]) for qi in range(8)]

    @pl.when(b == 0)
    def _():
        res[0, 0] = jnp.zeros((), p_out.dtype)
        vacc[...] = jnp.zeros_like(vacc)
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    octs = {bits: pw2[slot * 8 + qidx[bits]] for bits in BITS}
    rhs_o = {bits: rw2[slot * 8 + qidx[bits]] for bits in BITS}

    shape = octs[(0, 0, 0)].shape
    ss = b * bk - h + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    rr = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    cc = jax.lax.broadcasted_iota(jnp.int32, shape, 2)
    coords = (ss, rr, cc)
    extents = (k2, j2, i2)

    def ax_interior(axis, par):
        x, n = coords[axis], extents[axis]
        if par == 0:
            return (x >= 1) & (x <= n - 1)
        return (x >= 0) & (x <= n - 2)

    def interior(bits):
        return (ax_interior(0, bits[0]) & ax_interior(1, bits[1])
                & ax_interior(2, bits[2]))

    masks = {bits: interior(bits) for bits in BITS}

    def nbrs(bits):
        def ax_pair(axis):
            partner = octs[_flip(bits, axis)]
            if bits[axis] == 0:
                return jnp.roll(partner, 1, axis), partner
            return partner, jnp.roll(partner, -1, axis)

        f, bk_ = ax_pair(0)
        s_, n = ax_pair(1)
        w, e = ax_pair(2)
        return w, e, s_, n, f, bk_

    resids = {}
    for _t in range(n_inner):
        for group in (ODD, EVEN):
            for bits in group:
                c = octs[bits]
                w, e, s_, n, f, bk_ = nbrs(bits)
                r = rhs_o[bits] - (
                    (e - 2.0 * c + w) * idx2
                    + (n - 2.0 * c + s_) * idy2
                    + (bk_ - 2.0 * c + f) * idz2
                )
                rm = jnp.where(masks[bits], r, 0.0)
                octs[bits] = c - factor * rm
                resids[bits] = rm
        # Neumann refresh: 24 same-index plane selects
        for axis in range(3):
            for hi in (False, True):
                x, nax = coords[axis], extents[axis]
                plane = (x == nax - 1) if hi else (x == 0)
                for bits in BITS:
                    if bits[axis] != (1 if hi else 0):
                        continue
                    a2, a3 = [a for a in range(3) if a != axis]
                    sel = (plane & ax_interior(a2, bits[a2])
                           & ax_interior(a3, bits[a3]))
                    octs[bits] = jnp.where(
                        sel, octs[_flip(bits, axis)], octs[bits]
                    )

    @pl.when(b >= 2)
    def _():
        for c in store(b - 2, slot):
            c.wait()

    for bits in BITS:
        ob2[slot * 8 + qidx[bits]] = octs[bits][h: h + bk]
    for c in store(b, slot):
        c.start()

    acc = jnp.zeros_like(vacc[...])
    for bits in BITS:
        band = resids[bits][h: h + bk]
        acc = acc + jnp.sum(band * band, axis=(0, 1))[None, :]
    vacc[...] += acc

    @pl.when(b == nblocks - 1)
    def _():
        res[0, 0] += jnp.sum(vacc[...])
        for c in store(b, slot):
            c.wait()
        if nblocks > 1:
            for c in store(b - 1, nslot):
                c.wait()


def octants_padded_ji(jmax: int, imax: int, dtype) -> tuple[int, int]:
    """Octant in-plane padded shape: (jmax+2)/2 to the sublane tile,
    (imax+2)/2 to the lane tile."""
    a = _align(dtype)
    jp2 = -(-((jmax + 2) // 2) // a) * a
    ip2 = -(-((imax + 2) // 2) // LANE) * LANE
    return jp2, ip2


def pad_octants(p, block_k: int, n_inner: int):
    """(kmax+2, jmax+2, imax+2) even-shaped -> (8, sp, jp2, ip2) stacked
    padded octants in sor_octants.BITS order.

    Packing is STAGED single-axis stride-2 slices — one combined
    all-axes stride-2 gather per octant measured ~100 ms per NS-3D solve
    at 128³ on v5e, and the reshape-transpose alternative plans
    intermediates with a size-2 minor dim whose 128-lane tile padding OOMs
    the Mosaic/XLA compiler at large grids (f32[4097,2,4097,2] → 17 GB;
    see sor_pallas.pad_quarters). Axis-at-a-time slices (major-dim k split
    = strided DMA, then sublane j split, then lane i split on
    eighth-sized slabs) keep every intermediate in a sane layout."""
    K, J, I = p.shape
    k2, j2, i2 = K // 2, J // 2, I // 2
    slabs = {}
    for pk in (0, 1):
        sk = p[pk::2]
        for pj in (0, 1):
            skj = sk[:, pj::2]
            for pi in (0, 1):
                slabs[(pk, pj, pi)] = skj[:, :, pi::2]
    from .sor_octants import BITS

    stacked = jnp.stack([slabs[bits] for bits in BITS])
    jp2, ip2 = octants_padded_ji(J - 2, I - 2, p.dtype)
    nblocks = -(-k2 // block_k)
    sp = nblocks * block_k + 2 * n_inner
    out = jnp.zeros((8, sp, jp2, ip2), p.dtype)
    return out.at[:, n_inner: n_inner + k2, :j2, :i2].set(stacked)


def unpad_octants(xo, kmax: int, jmax: int, imax: int, n_inner: int):
    """Inverse of pad_octants, staged axis-at-a-time scatter form (lane
    interleave per (pk, pj) slab, then sublane, then outer — same
    layout-safety/perf constraint as pad_octants; a combined all-axes
    stride-2 scatter per octant mirrors the gather the pack refactor
    removed)."""
    from .sor_octants import BITS

    k2, j2, i2 = (kmax + 2) // 2, (jmax + 2) // 2, (imax + 2) // 2
    stacked = xo[:, n_inner: n_inner + k2, :j2, :i2]
    q = {bits: stacked[qi] for qi, bits in enumerate(BITS)}
    kj = {}
    for pk in (0, 1):
        for pj in (0, 1):
            m = jnp.zeros((k2, j2, 2 * i2), xo.dtype)
            m = m.at[:, :, 0::2].set(q[(pk, pj, 0)])
            m = m.at[:, :, 1::2].set(q[(pk, pj, 1)])
            kj[(pk, pj)] = m
    slabs = {}
    for pk in (0, 1):
        m = jnp.zeros((k2, 2 * j2, 2 * i2), xo.dtype)
        m = m.at[:, 0::2].set(kj[(pk, 0)])
        m = m.at[:, 1::2].set(kj[(pk, 1)])
        slabs[pk] = m
    p = jnp.zeros((2 * k2, 2 * j2, 2 * i2), xo.dtype)
    p = p.at[0::2].set(slabs[0])
    p = p.at[1::2].set(slabs[1])
    return p


def pick_block_k_octants(kmax: int, jmax: int, imax: int, dtype,
                         n_inner: int) -> int:
    """Octant planes per block. Resident octant planes: p windows
    16·(bk+2h) + rhs windows 16·(bk+2h) + store buffers 16·bk
    = 48·bk + 64·h, budgeted against ~half the VMEM limit (Mosaic
    temporaries — the 8 octant values and their rolls — take the rest).
    Getting this wrong crashes the remote Mosaic compiler outright
    (HTTP 500, no diagnostic), it does not error gracefully."""
    return max(1, min(_octants_feasible(jmax, imax, dtype, n_inner),
                      (kmax + 2) // 2, 64))


def _octants_feasible(jmax: int, imax: int, dtype, n_inner: int) -> int:
    """Largest VMEM-feasible octant block depth — the single home of the
    resident-plane accounting (pick_block_k_octants clamps it, the
    degenerate guard checks it; diverging copies would let an infeasible
    build through, which crashes the remote Mosaic compiler)."""
    jp2, ip2 = octants_padded_ji(jmax, imax, dtype)
    plane = jp2 * ip2 * jnp.dtype(dtype).itemsize
    return ((VMEM_LIMIT_BYTES // 2) // max(plane, 1) - 64 * n_inner) // 48


def block_k_octants_degenerate(block_k: int, kmax: int, jmax: int, imax: int,
                               dtype, n_inner: int) -> bool:
    """True when the VMEM budget (not the grid) forced the octant block
    size below feasibility: either the budget admits no block at all
    (feasible < 1 — pick clamps to 1, which n_inner=1 dispatch tests can't
    catch) or the block is thinner than the halo while the grid isn't.
    Mirrors block_k_degenerate for the checkerboard kernel."""
    if _octants_feasible(jmax, imax, dtype, n_inner) < 1:
        return True
    return block_k < n_inner and block_k < (kmax + 2) // 2


def make_rb_iter_tblock_3d_octants(
    imax: int,
    jmax: int,
    kmax: int,
    dx: float,
    dy: float,
    dz: float,
    omega: float,
    dtype,
    *,
    n_inner: int = 1,
    block_k: int | None = None,
    interpret: bool | None = None,
):
    """Temporal-blocked OCTANT-layout 3-D kernel: builds
    `(p_stacked, rhs_stacked) -> (p_stacked', res_sumsq_of_last_iter)` on
    the (8, sp, jp2, ip2) layout of `pad_octants`. Requires even
    imax/jmax/kmax. Returns (rb_iter, block_k, halo=n_inner). Numerics:
    ulp-equivalent to the masked paths (ops/sor_octants.py)."""
    if pltpu is None:
        return None, 0, 0
    if imax % 2 or jmax % 2 or kmax % 2:
        raise ValueError("octant layout needs even imax, jmax, kmax")
    if block_k is None:
        block_k = pick_block_k_octants(kmax, jmax, imax, dtype, n_inner)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)

    from ..models.ns3d import sor_coefficients_3d

    factor, idx2, idy2, idz2 = sor_coefficients_3d(dx, dy, dz, omega)
    h = n_inner
    k2, j2, i2 = (kmax + 2) // 2, (jmax + 2) // 2, (imax + 2) // 2
    jp2, ip2 = octants_padded_ji(jmax, imax, dtype)
    nblocks = -(-k2 // block_k)
    sp = nblocks * block_k + 2 * h
    kernel = functools.partial(
        _tblock3d_octants_kernel,
        n_inner=n_inner,
        block_k=block_k,
        nblocks=nblocks,
        k2=k2,
        j2=j2,
        i2=i2,
        halo=h,
        factor=factor,
        idx2=idx2,
        idy2=idy2,
        idz2=idz2,
    )
    call = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 1), lambda b: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((8, sp, jp2, ip2), dtype),
            jax.ShapeDtypeStruct((1, 1), dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((16, block_k + 2 * h, jp2, ip2), dtype),
            pltpu.VMEM((16, block_k + 2 * h, jp2, ip2), dtype),
            pltpu.VMEM((16, block_k, jp2, ip2), dtype),
            pltpu.VMEM((1, ip2), dtype),
            pltpu.SemaphoreType.DMA((2, 16)),
            pltpu.SemaphoreType.DMA((2, 8)),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )

    def rb_iter(p_stacked, rhs_stacked):
        p_stacked, res = call(p_stacked, rhs_stacked)
        return p_stacked, res[0, 0]

    return rb_iter, block_k, h


def make_octants_solve_loop(rb_iter, block_k: int, eff: int, norm: float,
                            eps: float, itermax: int,
                            kmax: int, jmax: int, imax: int, dtype):
    """make_tblock_solve_loop on the stacked OCTANT layout: same convergence
    contract, only the pad/unpad pair differs."""
    return make_tblock_solve_loop(
        rb_iter, block_k, eff, norm, eps, itermax, kmax, jmax, imax, dtype,
        pad=lambda x: pad_octants(x, block_k, eff),
        unpad=lambda xo: unpad_octants(xo, kmax, jmax, imax, eff),
    )


def make_tblock_solve_loop(rb_iter, block_k: int, eff: int, norm: float,
                           eps: float, itermax: int,
                           kmax: int, jmax: int, imax: int, dtype,
                           pad=None, unpad=None):
    """The tblock convergence loop every 3-D pressure solver shares
    (uniform: models/ns3d.make_pressure_solve_3d; masked:
    ops/obstacle3d.make_obstacle_solver_fn_3d; octants:
    make_octants_solve_loop via the pad/unpad overrides): carry the PADDED
    array, one rb_iter call = eff fused iterations, convergence checked
    every eff iterations (honest `it` accounting), optional PAMPI_DEBUG
    residual line per check."""
    from ..utils import flags as _flags

    epssq = eps * eps
    if pad is None:
        def pad(x):
            return pad_array_3d(x, block_k, eff)
    if unpad is None:
        def unpad(xp):
            return unpad_array_3d(xp, kmax, jmax, imax, eff)

    def solve(p, rhs):
        pp = pad(p)
        rp = pad(rhs)

        def cond(c):
            _, res, it = c
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(c):
            pp, _, it = c
            pp, rsq = rb_iter(pp, rp)
            res = rsq / norm
            if _flags.debug():
                jax.debug.print("{} Residuum: {}", it + (eff - 1), res)
            return pp, res, it + eff

        pp, res, it = jax.lax.while_loop(
            cond, body,
            (pp, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32)),
        )
        return unpad(pp), res, it

    return solve


_PROBE3D_OK: bool | None = None


def probe_pallas_3d() -> bool:
    """One-time smoke test of the 3-D kernel on the real backend (same
    contract as sor_pallas.probe_pallas): chip/toolchain-wide failures
    surface here once and the dispatcher falls back to jnp."""
    global _PROBE3D_OK
    if _PROBE3D_OK is None:
        try:
            rb, bk = make_rb_iter_tblock_3d(
                30, 30, 30, 1.0 / 30, 1.0 / 30, 1.0 / 30, 1.7, jnp.float32,
                n_inner=1, interpret=False,
            )
            z = pad_array_3d(jnp.zeros((32, 32, 32), jnp.float32), bk, 1)
            _, res = rb(z, z)
            float(res)  # force completion: async errors surface here
            _PROBE3D_OK = True
        except Exception as exc:  # lint: allow(broad-except) — probe contract: any failure means "don't dispatch"
            import warnings

            warnings.warn(
                f"pallas 3-D TPU kernel unavailable ({type(exc).__name__}); "
                "falling back to the jnp path",
                stacklevel=2,
            )
            _PROBE3D_OK = False
    return _PROBE3D_OK
