"""Red-black SOR as a Pallas TPU kernel — the framework's hot op.

Capability parity: the reference's red-black Poisson kernels
(/root/reference/assignment-4/src/solver.c: solveRB:179, solveRBA:240),
re-designed for the TPU memory hierarchy instead of translated:

- One `pallas_call` performs a FULL red-black iteration (both half-sweeps +
  the residual reduction). The jnp fallback (`ops/sor.py`) issues two fused
  XLA passes per iteration, each streaming p and rhs through HBM and
  allocating a fresh output; this kernel streams row blocks HBM->VMEM with
  explicit async DMA, updates p in place (input/output aliased), and
  accumulates the residual in SMEM.
- grid = (2, nblocks): the outer grid dimension is the color phase (0 = red,
  1 = black; same cell ordering as the reference's isw/jsw stride-2 loops),
  the inner is the row-block sweep. TPU grid steps execute sequentially, so
  the black phase reads the red phase's in-place updates — the Gauss-Seidel
  dependency the reference gets from its in-place double loop.
- The checkerboard is branch-free: a parity mask from `broadcasted_iota` on
  GLOBAL interior indices (i + j), applied to the update and the residual.
- In-place halo safety: a half-sweep modifies only parity-`phase` cells, and
  a block's halo rows contribute only opposite-parity neighbours, so the
  value an adjacent block reads is the same whether its window DMA lands
  before or after this block's write-back.

Alignment: Mosaic requires DMA slices aligned to the tile — sublane (8 for
f32) in dim 0, lane (128) in dim 1 — so the solver state lives in a PADDED
layout: `pad` rows of dead cells above and below the logical
(jmax+2, imax+2) array, and dead columns on the right up to the next lane
multiple. Each block owns an aligned band of `block_rows` padded rows (ghost
+ out-of-range rows masked out of the update), loads the aligned window
[band - pad, band + pad) at full padded width, and stores back exactly its
band. Dead columns are zero on entry and never written, so round-tripping
them through VMEM is harmless. `pad_array`/`unpad_array` convert at the loop
boundary only — the convergence loop carries the padded array, so padding
costs one copy per solve, not per iteration.

Layout: arrays are (jmax+2, imax+2) row-major [j, i] — i is the lane
dimension; padded shape ((nblocks*block_rows + 2*pad), lane_round(imax+2)).

Measured design notes (v5e, 4096² f32): n_inner=5 × block_rows=256 is the
sweep optimum (k=3..8 × 128/256/512). A compressed red-black layout
(separate dense red/black half-width arrays — all lanes productive, n/s
neighbours become pure sublane shifts) measured 1.6× SLOWER than the
masked checkerboard in like-for-like minimal kernels: the row-parity lane
selects (`where(row_even, x, roll(x))` per e/w neighbour) cost more than
the checkerboard masking they remove, so the masked form ships.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# jax renamed TPUCompilerParams -> CompilerParams; support both so the
# interpret-mode kernels (and their parity tests) run on either toolchain
CompilerParams = (
    getattr(pltpu, "CompilerParams", None)
    or getattr(pltpu, "TPUCompilerParams", None)
    if pltpu is not None
    else None
)


LANE = 128  # lane tile; DMA slice widths must be multiples of this

# v5e has 128 MiB of VMEM; the default scoped-vmem compile limit is 16 MiB,
# which caps the fused kernel at ~48-row blocks (one grid step per 48 rows —
# per-step overhead then dominates). Raised per-kernel via CompilerParams.
VMEM_LIMIT_BYTES = 100 << 20


def _align(dtype) -> int:
    """Sublane tile for the dtype (f32: 8, bf16: 16); DMA row offsets and
    lengths must be multiples of this."""
    return max(8, 32 // jnp.dtype(dtype).itemsize)


def padded_width(imax: int) -> int:
    """Logical width imax+2 rounded up to the lane tile."""
    return -(-(imax + 2) // LANE) * LANE


_PROBE_OK: bool | None = None


def probe_pallas() -> bool:
    """One-time smoke test: compile and run the fused kernel on a tiny grid
    on the real backend. Chip/toolchain-wide pallas failures (missing Mosaic
    support, tunnel compile errors) surface here once, letting the dispatcher
    fall back to the jnp path for every caller instead of crashing mid-run.
    Memoized per process; the probe shape hits the jit cache afterwards."""
    global _PROBE_OK
    if _PROBE_OK is None:
        try:
            rb, br, h = make_rb_iter_tblock(
                126, 126, 1.0 / 126, 1.0 / 126, 1.9, jnp.float32,
                n_inner=1, interpret=False,
            )
            z = pad_array(jnp.zeros((128, 128), jnp.float32), br, h)
            _, res = rb(z, z)
            float(res)  # force completion: async errors surface here
            _PROBE_OK = True
        except Exception as exc:  # lint: allow(broad-except) — probe contract: any failure means "don't dispatch"
            import warnings

            warnings.warn(
                f"pallas TPU kernel unavailable ({type(exc).__name__}); "
                "falling back to the jnp path",
                stacklevel=2,
            )
            _PROBE_OK = False
    return _PROBE_OK


def _check_dtype(dtype, interpret: bool) -> None:
    if not interpret and jnp.dtype(dtype).itemsize > 4:
        raise ValueError(
            f"Mosaic cannot lower {jnp.dtype(dtype).name} on TPU; use float32 "
            "(or bfloat16), or the jnp backend for float64"
        )


def pick_block_rows(jmax: int, imax: int, dtype=jnp.float32) -> int:
    """Largest aligned block height keeping the two VMEM windows
    ((BR+2A, Wp) + (BR, Wp)) under ~4 MiB, capped at one block per grid."""
    a = _align(dtype)
    itemsize = jnp.dtype(dtype).itemsize
    wp = padded_width(imax)
    budget = (4 << 20) // (2 * itemsize * wp)
    whole = -(-(jmax + 2) // a) * a  # one block covering everything
    br = max(a, min(budget // a * a, whole, 512))
    return br


def padded_rows(jmax: int, block_rows: int, dtype=jnp.float32,
                halo: int | None = None) -> int:
    a = halo if halo is not None else _align(dtype)
    nblocks = -(-(jmax + 2) // block_rows)
    return nblocks * block_rows + 2 * a


def pad_array(x, block_rows: int, halo: int | None = None):
    """(jmax+2, imax+2) -> padded layout; dead rows/columns are zero.
    `halo` rows of padding above/below (default: the sublane alignment)."""
    jmax = x.shape[0] - 2
    rp = padded_rows(jmax, block_rows, x.dtype, halo)
    a = halo if halo is not None else _align(x.dtype)
    out = jnp.zeros((rp, padded_width(x.shape[1] - 2)), x.dtype)
    return out.at[a : a + jmax + 2, : x.shape[1]].set(x)


def unpad_array(xp, jmax: int, imax: int, halo: int | None = None):
    a = halo if halo is not None else _align(xp.dtype)
    return xp[a : a + jmax + 2, : imax + 2]


def _rb_kernel(
    p_in,  # ANY (aliased to p_out) — unused; reads go through p_out
    rhs,  # ANY, padded like p
    p_out,  # ANY, aliased with p_in
    res,  # SMEM (1, 1) accumulator
    pw,  # VMEM (BR+2A, W) scratch: p window, owned band at rows [A, A+BR)
    rw,  # VMEM (BR, W) scratch: rhs band
    sem,  # DMA semaphores (2,)
    *,
    block_rows: int,
    width: int,
    jmax: int,
    pad: int,
    factor: float,
    idx2: float,
    idy2: float,
):
    del p_in
    phase = pl.program_id(0)  # 0 = red, 1 = black
    b = pl.program_id(1)
    br = block_rows
    a = pad
    band0 = a + b * br  # first padded row of the owned band

    ld_p = pltpu.make_async_copy(
        p_out.at[pl.ds(band0 - a, br + 2 * a), :], pw, sem.at[0]
    )
    ld_r = pltpu.make_async_copy(rhs.at[pl.ds(band0, br), :], rw, sem.at[1])
    ld_p.start()
    ld_r.start()
    ld_p.wait()
    ld_r.wait()

    c = pw[a : a + br, 1 : width - 1]
    east = pw[a : a + br, 2:width]
    west = pw[a : a + br, 0 : width - 2]
    north = pw[a + 1 : a + br + 1, 1 : width - 1]
    south = pw[a - 1 : a + br - 1, 1 : width - 1]
    lap = (east - 2.0 * c + west) * idx2 + (north - 2.0 * c + south) * idy2
    r = rw[:, 1 : width - 1] - lap

    # logical row j of local row l is b*br + l (padded row band0+l minus pad);
    # interior means 1 <= j <= jmax and the (i + j) checkerboard parity
    jj = b * br + jax.lax.broadcasted_iota(jnp.int32, r.shape, 0)
    ii = 1 + jax.lax.broadcasted_iota(jnp.int32, r.shape, 1)
    live = jnp.logical_and(
        ((ii + jj) % 2) == phase, jnp.logical_and(jj >= 1, jj <= jmax)
    )
    rm = jnp.where(live, r, jnp.zeros_like(r))

    pw[a : a + br, 1 : width - 1] = c - factor * rm

    @pl.when(jnp.logical_and(phase == 0, b == 0))
    def _():
        res[0, 0] = jnp.zeros((), rm.dtype)

    res[0, 0] += jnp.sum(rm * rm)

    st = pltpu.make_async_copy(
        pw.at[pl.ds(a, br), :], p_out.at[pl.ds(band0, br), :], sem.at[0]
    )
    st.start()
    st.wait()


def _tblock_kernel(
    *refs,
    n_inner: int,
    block_rows: int,
    nblocks: int,
    width: int,
    jmax: int,
    halo: int,
    factor: float,
    omega: float,
    idx2: float,
    idy2: float,
    masked: bool,
    dynamic: bool = False,
):
    """`n_inner` FULL red-black iterations (each incl. the Neumann ghost
    refresh) in a single HBM sweep — temporal blocking.

    One RB iteration consumes 2 rows of halo validity (red reads ±1 row,
    black reads red-updated values ±1 row), so a window of the owned band
    ±`halo` rows (halo ≥ 2·n_inner) yields a fully-converged owned band after
    n_inner iterations with no second HBM pass: HBM traffic per iteration
    drops to ~3/n_inner arrays. Halo rows are recomputed redundantly by both
    neighbouring blocks (identical values — same data, same unrolled
    arithmetic). The Neumann BC runs INSIDE the sweep between iterations
    (mask form of `neumann_bc_padded`: ghost rows/cols only, corners and
    dead padding untouched), because interior updates of iteration t+1 read
    ghost values refreshed after iteration t.

    masked=True adds a fluid-flag input (padded 0/1 array, ops/obstacle.py
    flag field) and switches the stencil to per-direction fluid coefficients
    with a per-cell relaxation factor ω/denom — homogeneous Neumann on
    obstacle surfaces, branch-free (the north-star requirement). The
    eps/factor arrays are derived from the flags ONCE per block, outside the
    iteration loop; arithmetic matches ops/obstacle.sor_pass_obstacle
    term-for-term. Flags are static config, so the extra HBM traffic is one
    array load per sweep (amortized over n_inner iterations).

    Residual: accumulated for the LAST iteration only (static slice of the
    owned band), so a convergence loop stepping this kernel observes the
    residual of its final iteration — the same value a per-iteration loop
    would see at that count.

    dynamic=True is the SHAPE-CLASS mode (fleet/shapeclass.py): the live
    extents and the grid-derived update constants arrive as SMEM scalars
    (ext int32 (1,2) = (jmax, imax); geo (1,3) = (factor, idx2, idy2))
    instead of trace constants, so one compiled kernel at the padded
    CLASS geometry serves every lane — the interior/parity/ghost masks
    are extent-gated per call and cells beyond the live extent pass
    through untouched (where-selects, never multiplies, so garbage
    there cannot reach any stored value or the residual).
    """
    if dynamic:
        (p_in, rhs, ext_ref, geo_ref, p_out, res,
         pw2, rw2, ob2, vacc, ld_sem, st_sem) = refs
        flg = fw2 = None
    elif masked:
        (p_in, rhs, flg, p_out, res,
         pw2, rw2, fw2, ob2, vacc, ld_sem, st_sem) = refs
    else:
        (p_in, rhs, p_out, res,
         pw2, rw2, ob2, vacc, ld_sem, st_sem) = refs
        flg = fw2 = None
    b = pl.program_id(0)
    br = block_rows
    h = halo
    slot = b % 2
    nslot = (b + 1) % 2

    def load(k, s):
        copies = [
            pltpu.make_async_copy(
                p_in.at[pl.ds(k * br, br + 2 * h), :], pw2.at[s], ld_sem.at[s, 0]
            ),
            pltpu.make_async_copy(
                rhs.at[pl.ds(k * br, br + 2 * h), :], rw2.at[s], ld_sem.at[s, 1]
            ),
        ]
        if masked:
            copies.append(
                pltpu.make_async_copy(
                    flg.at[pl.ds(k * br, br + 2 * h), :], fw2.at[s],
                    ld_sem.at[s, 2],
                )
            )
        return copies

    def store(k, s):
        return pltpu.make_async_copy(
            ob2.at[s], p_out.at[pl.ds(h + k * br, br), :], st_sem.at[s]
        )

    @pl.when(b == 0)
    def _():
        res[0, 0] = jnp.zeros((), p_out.dtype)
        vacc[...] = jnp.zeros_like(vacc)
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    p = pw2[slot]
    rw = rw2[slot]

    # logical (j, i) of window cell (w, c): j = b*br + w - h, i = c.
    # dynamic mode reads the live extents from SMEM (the static path's
    # `width - 2` IS its imax, so the two forms are the same masks)
    if dynamic:
        jmax = ext_ref[0, 0]
        imax_d = ext_ref[0, 1]
    else:
        imax_d = width - 2
    jj = b * br - h + jax.lax.broadcasted_iota(jnp.int32, p.shape, 0)
    ii = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
    interior = (jj >= 1) & (jj <= jmax) & (ii >= 1) & (ii <= imax_d)
    red = interior & (((ii + jj) % 2) == 0)
    black = interior & (((ii + jj) % 2) == 1)
    row_ghost_lo = (jj == 0) & (ii >= 1) & (ii <= imax_d)
    row_ghost_hi = (jj == jmax + 1) & (ii >= 1) & (ii <= imax_d)
    row_int = (jj >= 1) & (jj <= jmax)
    col_ghost_lo = (ii == 0) & row_int
    col_ghost_hi = (ii == imax_d + 1) & row_int

    if masked:
        # per-block constants (flags don't change across inner iterations):
        # eps_d = "neighbour in direction d is fluid"; the update factor is
        # ω/denom on fluid cells, 0 elsewhere (ops/obstacle.make_masks parity)
        fl = fw2[slot]
        red = red & (fl != 0)
        black = black & (fl != 0)
        fac, lap = masked_stencil_ops(fl, idx2, idy2, omega)
    else:
        if dynamic:
            # per-lane update constants (computed host-side in Python f64
            # with the solo solver's own expressions — the shape-class
            # bitwise-coefficient contract)
            fac = geo_ref[0, 0]
            idx2 = geo_ref[0, 1]
            idy2 = geo_ref[0, 2]
        else:
            fac = factor

        def lap(x):
            east = jnp.roll(x, -1, axis=1)
            west = jnp.roll(x, 1, axis=1)
            north = jnp.roll(x, -1, axis=0)
            south = jnp.roll(x, 1, axis=0)
            return (east - 2.0 * x + west) * idx2 + (
                north - 2.0 * x + south
            ) * idy2

    p, r_red, r_blk = rb_inner_sweeps(
        p, rw, n_inner, red, black, fac, lap,
        (row_ghost_lo, row_ghost_hi, col_ghost_lo, col_ghost_hi),
    )

    @pl.when(b >= 2)
    def _():
        store(b - 2, slot).wait()

    ob2[slot] = p[h : h + br, :]
    store(b, slot).start()

    # residual of the final iteration, owned band only (static slice).
    # Reduce along sublanes only and accumulate a per-lane vector; the
    # expensive cross-lane reduction happens ONCE in the last block instead
    # of per block (measured ~25% of kernel time when done per block).
    ro = r_red[h : h + br, :]
    bo = r_blk[h : h + br, :]
    vacc[...] += jnp.sum(ro * ro + bo * bo, axis=0, keepdims=True)

    @pl.when(b == nblocks - 1)
    def _():
        res[0, 0] += jnp.sum(vacc[...])

    @pl.when(b == nblocks - 1)
    def _():
        store(b, slot).wait()
        if nblocks > 1:  # static: drain the previous slot's store too
            store(b - 1, nslot).wait()


def tblock_halo(n_inner: int, dtype) -> int:
    """Window halo for n_inner fused iterations: 2 rows per iteration,
    rounded up to the DMA sublane alignment."""
    a = _align(dtype)
    return max(a, -(-(2 * n_inner) // a) * a)


def masked_stencil_ops(fl, idx2, idy2, omega):
    """(fac, lap) for the flag-masked (obstacle) stencil, derived from a
    0/1 flag window — the SINGLE home of the eps-coefficient kernel math
    (used by _tblock_kernel's masked mode and the distributed
    ops/sor_obsdist kernel; flag values are identical on every shard that
    sees a cell, so sharing this keeps the two term-for-term identical).
    Arithmetic matches ops/obstacle.sor_pass_obstacle."""
    eps_e = jnp.roll(fl, -1, axis=1)
    eps_w = jnp.roll(fl, 1, axis=1)
    eps_n = jnp.roll(fl, -1, axis=0)
    eps_s = jnp.roll(fl, 1, axis=0)
    denom = (eps_e + eps_w) * idx2 + (eps_n + eps_s) * idy2
    fac = jnp.where(denom > 0, omega / denom, 0.0) * fl

    def lap(x):
        east = jnp.roll(x, -1, axis=1)
        west = jnp.roll(x, 1, axis=1)
        north = jnp.roll(x, -1, axis=0)
        south = jnp.roll(x, 1, axis=0)
        return (eps_e * (east - x) + eps_w * (west - x)) * idx2 + (
            eps_n * (north - x) + eps_s * (south - x)
        ) * idy2

    return fac, lap


def rb_inner_sweeps(p, rw, n_inner, red, black, fac, lap, ghosts,
                    loop: bool = False):
    """The fused red-black inner loop + per-iteration Neumann ghost refresh
    shared by every 2-D checkerboard-layout kernel (single-device
    _tblock_kernel and distributed _obsdist_kernel — one home so the two
    cannot drift). `ghosts` = (row_lo, row_hi, col_lo, col_hi) select
    masks. Returns (p, r_red, r_blk) of the LAST iteration.

    `loop=True` runs the sweeps through a `lax.fori_loop` (scf.for in
    Mosaic) instead of unrolling: Mosaic's STACK for the unrolled body
    scales with n (each unrolled sweep keeps window-sized temporaries
    live — the ca16-at-512-wide-shards OOM of round 4), while the looped
    body's live set is one sweep's. Same op sequence per sweep -> bitwise
    identical results; the default stays unrolled (the tuned headline
    kernels' codegen is untouched)."""
    row_lo, row_hi, col_lo, col_hi = ghosts

    def sweep(p):
        r_red = jnp.where(red, rw - lap(p), 0.0)
        p = p - fac * r_red
        r_blk = jnp.where(black, rw - lap(p), 0.0)
        p = p - fac * r_blk
        p = jnp.where(row_lo, jnp.roll(p, -1, axis=0), p)
        p = jnp.where(row_hi, jnp.roll(p, 1, axis=0), p)
        p = jnp.where(col_lo, jnp.roll(p, -1, axis=1), p)
        p = jnp.where(col_hi, jnp.roll(p, 1, axis=1), p)
        return p, r_red, r_blk

    if loop:
        return jax.lax.fori_loop(
            0, n_inner, lambda _t, c: sweep(c[0]),
            (p, jnp.zeros_like(p), jnp.zeros_like(p)),
        )
    r_red = r_blk = None
    for _t in range(n_inner):
        p, r_red, r_blk = sweep(p)
    return p, r_red, r_blk


def pick_block_rows_tblock(jmax: int, imax: int, dtype=jnp.float32,
                           n_inner: int = 4) -> int:
    """Block height for the temporal-blocked kernel. The round-2 sweep
    (tools/perf_sweep_tblock.py, dispatch-latency-amortized: SWEEP_TOTAL=960,
    k ∈ {3..8} × br ∈ {64..256} at 4096² f32, and the 8192² region harness)
    measured a flat surface 36-41G updates/s with the optimum at 128 rows
    for BOTH 4224- and 8320-lane widths — so large grids get a flat 128.
    Small grids keep the single-block window (no redundant halo recompute;
    the window fits VMEM outright)."""
    a = _align(dtype)
    h = tblock_halo(n_inner, dtype)
    wp = padded_width(imax)
    whole = -(-(jmax + 2) // a) * a  # one block covering everything
    if whole >= 1024:
        return max(a, h, 128)
    target = 256 * 4224 * 4  # bytes per window buffer that fit comfortably
    br = target // (wp * jnp.dtype(dtype).itemsize) // a * a
    return max(a, h, min(br, 512, whole))


def tblock_vmem_bytes(block_rows: int, h: int, wp: int, itemsize: int,
                      masked: bool = False) -> int:
    """Scratch bytes of the checkerboard tblock kernel: double-buffered p and
    rhs (+ flag) windows, out bands, per-lane accumulator."""
    nwin = 3 if masked else 2
    win = 2 * (block_rows + 2 * h) * wp
    return itemsize * (nwin * win + 2 * block_rows * wp + wp)


def tblock_feasible(block_rows: int, h: int, wp: int, itemsize: int,
                    masked: bool = False) -> bool:
    """VMEM guard for the checkerboard kernel (same contract as
    quarters_feasible — an infeasible build crashes Mosaic at first
    dispatch, so the dispatcher must get a catchable error instead)."""
    return (
        tblock_vmem_bytes(block_rows, h, wp, itemsize, masked)
        <= VMEM_LIMIT_BYTES // 2
    )


def make_rb_iter_tblock(
    imax: int,
    jmax: int,
    dx: float,
    dy: float,
    omega: float,
    dtype,
    *,
    n_inner: int = 4,
    block_rows: int | None = None,
    interpret: bool | None = None,
    fluid=None,
    dynamic: bool = False,
):
    """Temporal-blocked fused kernel (see `_tblock_kernel`): builds
    `(p_padded, rhs_padded) -> (p_padded', res_sumsq_of_last_iter)` where one
    call performs `n_inner` red-black iterations + Neumann BCs. The padded
    layout uses `halo = tblock_halo(n_inner)` rows of padding (pass it to
    `pad_array`/`unpad_array`). Returns (rb_iter, block_rows, halo).

    fluid: optional (jmax+2, imax+2) 0/1 flag field (ops/obstacle.py) —
    switches to the obstacle stencil (per-direction fluid coefficients,
    per-cell factor); the padded flag array is baked into the returned
    closure as a constant.

    dynamic=True (the shape-class padded-layout solve): imax/jmax set the
    padded CLASS geometry only; the live extents and update constants are
    call-time SMEM scalars, so rb_iter becomes
    `(p_padded, rhs_padded, ext_i32_12, geo_13) -> (p', res_sumsq)` with
    ext = (jmax, imax) and geo = (factor, idx2, idy2). Incompatible with
    `fluid` (obstacle lanes are class-ineligible)."""
    if pltpu is None:
        return None, 0, 0
    if dynamic and fluid is not None:
        raise ValueError("dynamic extents and obstacle flags are exclusive")
    h = tblock_halo(n_inner, dtype)
    if block_rows is None:
        block_rows = pick_block_rows_tblock(jmax, imax, dtype, n_inner)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)
    masked = fluid is not None
    itemsize = jnp.dtype(dtype).itemsize
    if not tblock_feasible(block_rows, h, padded_width(imax), itemsize,
                           masked):
        raise ValueError(
            f"tblock scratch {tblock_vmem_bytes(block_rows, h, padded_width(imax), itemsize, masked) >> 20} MiB "
            f"exceeds the VMEM budget (block_rows={block_rows}, h={h}, "
            f"wp={padded_width(imax)}); the grid is too wide for the fused "
            "kernel — the jnp path is the fallback"
        )

    dx2, dy2 = dx * dx, dy * dy
    width = imax + 2
    wp = padded_width(imax)
    nblocks = -(-(jmax + 2) // block_rows)
    rp = nblocks * block_rows + 2 * h
    kernel = functools.partial(
        _tblock_kernel,
        n_inner=n_inner,
        block_rows=block_rows,
        nblocks=nblocks,
        width=width,
        jmax=jmax,
        halo=h,
        factor=omega * 0.5 * (dx2 * dy2) / (dx2 + dy2),
        omega=omega,
        idx2=1.0 / dx2,
        idy2=1.0 / dy2,
        masked=masked,
        dynamic=dynamic,
    )

    n_any = 3 if masked else 2  # DMA'd HBM operands (sem count)
    scratch = [
        pltpu.VMEM((2, block_rows + 2 * h, wp), dtype),
        pltpu.VMEM((2, block_rows + 2 * h, wp), dtype),
    ]
    if masked:
        scratch.append(pltpu.VMEM((2, block_rows + 2 * h, wp), dtype))
    scratch += [
        pltpu.VMEM((2, block_rows, wp), dtype),
        pltpu.VMEM((1, wp), dtype),  # per-lane residual accumulator
        pltpu.SemaphoreType.DMA((2, n_any)),
        pltpu.SemaphoreType.DMA((2,)),
    ]
    in_specs = [pl.BlockSpec(memory_space=pl.ANY)] * n_any
    if dynamic:
        # the per-lane extent/constant scalars ride SMEM after the arrays
        in_specs += [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
    call = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 1), lambda b: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, wp), dtype),
            jax.ShapeDtypeStruct((1, 1), dtype),
        ],
        scratch_shapes=scratch,
        compiler_params=CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )

    if dynamic:

        def rb_iter(p_padded, rhs_padded, ext, geo):
            p_padded, res = call(p_padded, rhs_padded, ext, geo)
            return p_padded, res[0, 0]
    elif masked:
        flg_padded = pad_array(jnp.asarray(fluid, dtype), block_rows, h)

        def rb_iter(p_padded, rhs_padded):
            p_padded, res = call(p_padded, rhs_padded, flg_padded)
            return p_padded, res[0, 0]
    else:

        def rb_iter(p_padded, rhs_padded):
            p_padded, res = call(p_padded, rhs_padded)
            return p_padded, res[0, 0]

    return rb_iter, block_rows, h


def _tblock_quarters_kernel(
    p_in,   # ANY (4, rp, W2p) stacked quarters [R0, R1, B0, B1]
    rhs,    # ANY (4, rp, W2p) stacked rhs quarters [F0, F1, G0, G1]
    p_out,  # ANY (4, rp, W2p)
    res,    # SMEM (1, 1)
    pw2,    # VMEM (2, 4, brq+2h, W2p) p windows, double-buffered
    rw2,    # VMEM (2, 4, brq+2h, W2p) rhs windows
    ob2,    # VMEM (2, 4, brq, W2p) out bands
    vacc,   # VMEM (1, W2p) per-lane residual accumulator
    ld_sem,  # DMA (2, 8)
    st_sem,  # DMA (2, 4)
    *,
    n_inner: int,
    block_rows: int,  # quarter rows per block
    nblocks: int,
    j2: int,   # (jmax+2)//2 logical quarter rows
    i2: int,   # (imax+2)//2 logical quarter lanes
    halo: int,
    factor: float,
    idx2: float,
    idy2: float,
    compute_dtype=None,
):
    """Temporal-blocked red-black sweep in the QUARTER layout
    (ops/sor_quarters.py derivation): every neighbour a uniform ±1 shift,
    every lane productive, the Neumann refresh 8 same-index edge selects.
    One iteration consumes ONE quarter-row of halo per side (= 2 grid rows,
    matching the checkerboard kernel's 2·n_inner grid-row halo).

    compute_dtype: when set (the bf16-storage mode), windows are loaded in
    the storage dtype (half the HBM traffic and VMEM footprint), upcast once
    per block, iterated in compute_dtype (f32), and downcast at the store —
    bf16 touches only the HBM arrays, never the arithmetic."""
    b = pl.program_id(0)
    brq = block_rows
    h = halo
    slot = b % 2
    nslot = (b + 1) % 2

    def load(k, s):
        copies = []
        for qi in range(4):
            copies.append(pltpu.make_async_copy(
                p_in.at[qi, pl.ds(k * brq, brq + 2 * h), :],
                pw2.at[s, qi], ld_sem.at[s, qi]))
            copies.append(pltpu.make_async_copy(
                rhs.at[qi, pl.ds(k * brq, brq + 2 * h), :],
                rw2.at[s, qi], ld_sem.at[s, 4 + qi]))
        return copies

    def store(k, s):
        return [pltpu.make_async_copy(
            ob2.at[s, qi], p_out.at[qi, pl.ds(h + k * brq, brq), :],
            st_sem.at[s, qi]) for qi in range(4)]

    @pl.when(b == 0)
    def _():
        res[0, 0] = jnp.zeros((), res.dtype)
        vacc[...] = jnp.zeros_like(vacc)
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    R0, R1, B0, B1 = (pw2[slot, qi] for qi in range(4))
    F0, F1, G0, G1 = (rw2[slot, qi] for qi in range(4))
    if compute_dtype is not None:
        R0, R1, B0, B1 = (x.astype(compute_dtype) for x in (R0, R1, B0, B1))
        F0, F1, G0, G1 = (x.astype(compute_dtype) for x in (F0, F1, G0, G1))

    # quarter-space coordinates of window cell (w, c): r = b*brq - h + w
    rr = b * brq - h + jax.lax.broadcasted_iota(jnp.int32, R0.shape, 0)
    cc = jax.lax.broadcasted_iota(jnp.int32, R0.shape, 1)
    # rectangular interiors per quarter (module docstring of sor_quarters)
    m_r0 = (rr >= 1) & (rr <= j2 - 1) & (cc >= 1) & (cc <= i2 - 1)
    m_r1 = (rr >= 0) & (rr <= j2 - 2) & (cc <= i2 - 2)
    m_b0 = (rr >= 1) & (rr <= j2 - 1) & (cc <= i2 - 2)
    m_b1 = (rr >= 0) & (rr <= j2 - 2) & (cc >= 1) & (cc <= i2 - 1)
    # Neumann edge-strip selects (same-index copies between quarters)
    row_lo = rr == 0
    row_hi = rr == j2 - 1
    col_lo = cc == 0
    col_hi_even = cc == i2 - 1   # i = imax (even-i quarters' last lane)
    j_int_even = (rr >= 1) & (rr <= j2 - 1)
    j_int_odd = (rr >= 0) & (rr <= j2 - 2)

    def upd(center, rhs_q, w, e, s, n, mask):
        r = rhs_q - ((e - 2.0 * center + w) * idx2
                     + (n - 2.0 * center + s) * idy2)
        rm = jnp.where(mask, r, 0.0)
        return center - factor * rm, rm

    def east(x):
        return jnp.roll(x, -1, axis=1)

    def west(x):
        return jnp.roll(x, 1, axis=1)

    def north(x):
        return jnp.roll(x, -1, axis=0)

    def south(x):
        return jnp.roll(x, 1, axis=0)

    r0 = r1 = r2 = r3 = None
    for _ in range(n_inner):
        # red pass (reads black)
        R0, r0 = upd(R0, F0, west(B0), B0, south(B1), B1, m_r0)
        R1, r1 = upd(R1, F1, B1, east(B1), B0, north(B0), m_r1)
        # black pass (reads updated red)
        B0, r2 = upd(B0, G0, R0, east(R0), south(R1), R1, m_b0)
        B1, r3 = upd(B1, G1, west(R1), R1, R0, north(R0), m_b1)
        # Neumann ghost refresh: 8 same-index edge selects
        R0 = jnp.where(row_lo & (cc >= 1) & (cc <= i2 - 1), B1, R0)
        B0 = jnp.where(row_lo & (cc <= i2 - 2), R1, B0)
        R1 = jnp.where(row_hi & (cc <= i2 - 2), B0, R1)
        B1 = jnp.where(row_hi & (cc >= 1) & (cc <= i2 - 1), R0, B1)
        R0 = jnp.where(col_lo & j_int_even, B0, R0)
        B1 = jnp.where(col_lo & j_int_odd, R1, B1)
        B0 = jnp.where(col_hi_even & j_int_even, R0, B0)
        R1 = jnp.where(col_hi_even & j_int_odd, B1, R1)

    @pl.when(b >= 2)
    def _():
        for c in store(b - 2, slot):
            c.wait()

    for qi, arr in enumerate((R0, R1, B0, B1)):
        band = arr[h: h + brq, :]
        if compute_dtype is not None:
            band = band.astype(p_out.dtype)
        ob2[slot, qi] = band
    for c in store(b, slot):
        c.start()

    # residual of the final iteration, owned bands only
    acc = jnp.zeros_like(vacc[...])
    for rq in (r0, r1, r2, r3):
        band = rq[h: h + brq, :]
        acc = acc + jnp.sum(band * band, axis=0, keepdims=True)
    vacc[...] += acc

    @pl.when(b == nblocks - 1)
    def _():
        res[0, 0] += jnp.sum(vacc[...])

    @pl.when(b == nblocks - 1)
    def _():
        for c in store(b, slot):
            c.wait()
        if nblocks > 1:
            for c in store(b - 1, nslot):
                c.wait()


def quarters_halo(n_inner: int, dtype) -> int:
    """Quarter-row halo for n_inner fused iterations: 1 quarter row per
    iteration per side, rounded to the sublane alignment."""
    a = _align(dtype)
    return max(a, -(-n_inner // a) * a)


def pad_quarters(p, block_rows_q: int, halo: int):
    """(jmax+2, imax+2) even-shaped array -> (4, rp, W2p) stacked padded
    quarter layout [R0, R1, B0, B1].

    LAYOUT SAFETY: any intermediate with a size-2 dim in the minor-two
    (tiled) positions explodes — [j2, 2, i2, 2] tiles the trailing 2 to a
    128-lane tile, a 64× blowup that OOMs the compiler outright at 8192²
    (f32[4097,2,4097,2] plans as 17 GB). Packing therefore uses staged
    single-axis stride-2 slices (outer-dim row split is a strided DMA,
    lane split a lane gather on the halved rows), which keep every
    intermediate in a sane layout."""
    J, I = p.shape
    j2, i2 = J // 2, I // 2
    r_even, r_odd = p[0::2], p[1::2]
    stacked = jnp.stack([
        r_even[:, 0::2],  # R0
        r_odd[:, 1::2],   # R1
        r_even[:, 1::2],  # B0
        r_odd[:, 0::2],   # B1
    ])
    nblocks = -(-j2 // block_rows_q)
    rp = nblocks * block_rows_q + 2 * halo
    w2p = -(-i2 // LANE) * LANE
    out = jnp.zeros((4, rp, w2p), p.dtype)
    return out.at[:, halo: halo + j2, :i2].set(stacked)


def unpad_quarters(xq, jmax: int, imax: int, halo: int):
    """Inverse of pad_quarters -> (jmax+2, imax+2), staged axis-at-a-time
    scatter form (lane interleave per row parity, then row interleave —
    same layout-safety/perf constraint as pad_quarters)."""
    j2, i2 = (jmax + 2) // 2, (imax + 2) // 2
    q = xq[:, halo: halo + j2, :i2]  # [R0, R1, B0, B1]
    r_even = jnp.zeros((j2, 2 * i2), xq.dtype)
    r_even = r_even.at[:, 0::2].set(q[0])  # R0
    r_even = r_even.at[:, 1::2].set(q[2])  # B0
    r_odd = jnp.zeros((j2, 2 * i2), xq.dtype)
    r_odd = r_odd.at[:, 0::2].set(q[3])   # B1
    r_odd = r_odd.at[:, 1::2].set(q[1])   # R1
    p = jnp.zeros((2 * j2, 2 * i2), xq.dtype)
    p = p.at[0::2].set(r_even)
    p = p.at[1::2].set(r_odd)
    return p


def quarters_vmem_bytes(brq: int, h: int, w2p: int, itemsize: int) -> int:
    """Scratch bytes of the quarters kernels (single-device and distributed
    share the buffer set): double-buffered p and rhs windows, out bands,
    per-lane accumulator."""
    win = 2 * 4 * (brq + 2 * h) * w2p
    return itemsize * (2 * win + 2 * 4 * brq * w2p + w2p)


def quarters_feasible(brq: int, h: int, w2p: int, itemsize: int) -> bool:
    """VMEM-feasibility guard (mirrors the octant accounting of
    sor3d_pallas._octants_feasible): the scratch set must fit the raised
    compile limit with headroom for Mosaic's own temporaries. A forced
    quarters layout on an extremely wide grid would otherwise crash the
    Mosaic compiler at first dispatch."""
    return quarters_vmem_bytes(brq, h, w2p, itemsize) <= VMEM_LIMIT_BYTES // 2


def make_rb_iter_tblock_quarters(
    imax: int,
    jmax: int,
    dx: float,
    dy: float,
    omega: float,
    dtype,
    *,
    n_inner: int = 4,
    block_rows_q: int | None = None,
    interpret: bool | None = None,
):
    """Temporal-blocked QUARTER-layout kernel: builds
    `(p_stacked, rhs_stacked) -> (p_stacked', res_sumsq_of_last_iter)`
    on the (4, rp, W2p) layout of `pad_quarters`. Requires even imax/jmax.
    Returns (rb_iter, block_rows_q, halo).

    Numerics: per-cell arithmetic keeps the reference association and is
    ulp-equivalent to the masked paths (compiler fma/fusion differences
    only — ops/sor_quarters.py); the residual summation order differs.

    bfloat16 `dtype` selects the bf16-storage / f32-compute mode: the HBM
    arrays and VMEM windows are bf16 (half the bytes on the roofline's HBM
    wall), the per-block iteration runs in f32, and the residual is
    accumulated and returned in f32 (bf16's 8-bit mantissa cannot hold a
    meaningful sum of squares)."""
    if pltpu is None:
        return None, 0, 0
    if imax % 2 or jmax % 2:
        raise ValueError("quarter layout needs even imax and jmax")
    h = quarters_halo(n_inner, dtype)
    if block_rows_q is None:
        # round-2 optimum at n_inner<=8 was 64 quarter-rows (= 128 grid
        # rows); the round-3 depth sweep (4096² f32, 3 same-session runs)
        # found deeper blocking wants taller blocks to amortize the larger
        # halo recompute: n16/brq128 measures 127-131G vs n8/brq64's
        # 76-84G, with n20+ falling off again (h=24 recompute)
        j2 = (jmax + 2) // 2
        whole = -(-j2 // _align(dtype)) * _align(dtype)
        base = 64 if n_inner < 12 else 128
        block_rows_q = max(_align(dtype), h, min(base, whole))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)

    dx2, dy2 = dx * dx, dy * dy
    j2, i2 = (jmax + 2) // 2, (imax + 2) // 2
    w2p = -(-i2 // LANE) * LANE
    nblocks = -(-j2 // block_rows_q)
    rp = nblocks * block_rows_q + 2 * h
    itemsize = jnp.dtype(dtype).itemsize
    if not quarters_feasible(block_rows_q, h, w2p, itemsize):
        raise ValueError(
            f"quarters scratch {quarters_vmem_bytes(block_rows_q, h, w2p, itemsize) >> 20} MiB "
            f"exceeds the VMEM budget (brq={block_rows_q}, h={h}, "
            f"w2p={w2p}); reduce tpu_sor_inner or use tpu_sor_layout "
            "checkerboard"
        )
    # bf16 storage iterates in f32 (see docstring); f32/f64 compute as stored
    bf16 = jnp.dtype(dtype) == jnp.dtype(jnp.bfloat16)
    compute_dtype = jnp.float32 if bf16 else None
    acc_dtype = jnp.float32 if bf16 else dtype
    kernel = functools.partial(
        _tblock_quarters_kernel,
        n_inner=n_inner,
        block_rows=block_rows_q,
        nblocks=nblocks,
        j2=j2,
        i2=i2,
        halo=h,
        factor=omega * 0.5 * (dx2 * dy2) / (dx2 + dy2),
        idx2=1.0 / dx2,
        idy2=1.0 / dy2,
        compute_dtype=compute_dtype,
    )
    call = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 1), lambda b: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((4, rp, w2p), dtype),
            jax.ShapeDtypeStruct((1, 1), acc_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, 4, block_rows_q + 2 * h, w2p), dtype),
            pltpu.VMEM((2, 4, block_rows_q + 2 * h, w2p), dtype),
            pltpu.VMEM((2, 4, block_rows_q, w2p), dtype),
            pltpu.VMEM((1, w2p), acc_dtype),
            pltpu.SemaphoreType.DMA((2, 8)),
            pltpu.SemaphoreType.DMA((2, 4)),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )

    def rb_iter(p_stacked, rhs_stacked):
        p_stacked, res = call(p_stacked, rhs_stacked)
        return p_stacked, res[0, 0]

    return rb_iter, block_rows_q, h


def neumann_bc_padded(p, jmax: int, imax: int):
    """Homogeneous-Neumann ghost copy in the padded layout (parity with
    ops/sor.py `neumann_bc`: walls only, corners untouched)."""
    a = _align(p.dtype)
    lo, hi = a, a + jmax + 1  # padded indices of the ghost rows
    p = p.at[lo, 1 : imax + 1].set(p[lo + 1, 1 : imax + 1])
    p = p.at[hi, 1 : imax + 1].set(p[hi - 1, 1 : imax + 1])
    p = p.at[lo + 1 : hi, 0].set(p[lo + 1 : hi, 1])
    p = p.at[lo + 1 : hi, imax + 1].set(p[lo + 1 : hi, imax])
    return p


def make_rb_iter_pallas(
    imax: int,
    jmax: int,
    dx: float,
    dy: float,
    omega: float,
    dtype,
    *,
    block_rows: int | None = None,
    interpret: bool | None = None,
):
    """Build `(p_padded, rhs_padded) -> (p_padded', res_sumsq)`: one full
    red-black SOR iteration (red then black half-sweep) with the
    un-normalized residual sum of r² over both sweeps. Operates on the padded
    layout (`pad_array`/`unpad_array`); returns (rb_iter, block_rows)."""
    if pltpu is None:
        return None, 0
    if block_rows is None:
        block_rows = pick_block_rows(jmax, imax, dtype)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)

    dx2, dy2 = dx * dx, dy * dy
    width = imax + 2
    wp = padded_width(imax)
    a = _align(dtype)
    kernel = functools.partial(
        _rb_kernel,
        block_rows=block_rows,
        width=width,
        jmax=jmax,
        pad=a,
        factor=omega * 0.5 * (dx2 * dy2) / (dx2 + dy2),
        idx2=1.0 / dx2,
        idy2=1.0 / dy2,
    )
    nblocks = -(-(jmax + 2) // block_rows)
    rp = nblocks * block_rows + 2 * a

    call = pl.pallas_call(
        kernel,
        grid=(2, nblocks),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 1), lambda phase, b: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, wp), dtype),
            jax.ShapeDtypeStruct((1, 1), dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_rows + 2 * a, wp), dtype),
            pltpu.VMEM((block_rows, wp), dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        input_output_aliases={0: 0},
        interpret=interpret,
    )

    def rb_iter(p_padded, rhs_padded):
        p_padded, res = call(p_padded, rhs_padded)
        return p_padded, res[0, 0]

    return rb_iter, block_rows
