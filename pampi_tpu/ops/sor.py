"""Red-black SOR building blocks, branch-free for TPU.

Capability parity with the reference's Poisson kernels
(/root/reference/assignment-4/src/solver.c: `solve`:126, `solveRB`:179,
`solveRBA`:240) re-designed TPU-first: instead of an in-place double loop with
`isw/jsw` checkerboard strides, each half-sweep is a masked, fully-vectorized
update over the whole interior — XLA fuses the 5-point stencil, the mask apply,
and the residual reduction into one pass over the array. The checkerboard mask
replaces control flow (TPUs want branch-free inner loops), and the two
half-sweeps (red = (i+j) even, black = odd, 1-based interior indices — the
exact cells the reference's stride-2 loops visit) preserve the Gauss-Seidel
dependency structure: the black pass sees the red pass's updated values.

Arrays are (jmax+2, imax+2), layout [j, i] — j rows, i contiguous (lane dim).
"""

from __future__ import annotations

import jax.numpy as jnp


def checkerboard_mask(jmax: int, imax: int, parity: int, dtype) -> jnp.ndarray:
    """Interior-cell mask (jmax, imax): 1 where (i + j) % 2 == parity.

    i, j are the reference's 1-based interior indices. parity=0 is the "red"
    pass (the reference's first pass: jsw=1 ⇒ visits i+j even), parity=1 black.
    """
    jj = jnp.arange(1, jmax + 1, dtype=jnp.int32)[:, None]
    ii = jnp.arange(1, imax + 1, dtype=jnp.int32)[None, :]
    return (((ii + jj) % 2) == parity).astype(dtype)


def _interior_residual(p, rhs, idx2, idy2):
    """Pointwise residual r = rhs - lap(p) on the interior (jmax, imax)."""
    lap = (p[1:-1, 2:] - 2.0 * p[1:-1, 1:-1] + p[1:-1, :-2]) * idx2 + (
        p[2:, 1:-1] - 2.0 * p[1:-1, 1:-1] + p[:-2, 1:-1]
    ) * idy2
    return rhs[1:-1, 1:-1] - lap


def sor_pass(p, rhs, mask, factor, idx2, idy2):
    """One masked half-sweep. Returns (updated p, sum of masked r²).

    Matches the arithmetic of the reference's per-cell body
    (assignment-4/src/solver.c:205-212): r = rhs - lap(p); p -= factor*r;
    res += r*r — restricted to `mask` cells.
    """
    r = _interior_residual(p, rhs, idx2, idy2) * mask
    p = p.at[1:-1, 1:-1].add(-factor * r)
    return p, jnp.sum(r * r)


def residual_all(p, rhs, idx2, idy2):
    """Unmasked interior residual sum-of-squares (diagnostic)."""
    r = _interior_residual(p, rhs, idx2, idy2)
    return jnp.sum(r * r)


def neumann_bc(p):
    """Homogeneous-Neumann ghost copy on all four walls, corners untouched
    (parity: assignment-4/src/solver.c:157-165 — loops run 1..imax/1..jmax,
    so corner ghosts keep their init values; replicated for bitwise output
    parity of the full-array p.dat writer)."""
    p = p.at[0, 1:-1].set(p[1, 1:-1])
    p = p.at[-1, 1:-1].set(p[-2, 1:-1])
    p = p.at[1:-1, 0].set(p[1:-1, 1])
    p = p.at[1:-1, -1].set(p[1:-1, -2])
    return p
