"""Red-black SOR building blocks, branch-free for TPU.

Capability parity with the reference's Poisson kernels
(/root/reference/assignment-4/src/solver.c: `solve`:126, `solveRB`:179,
`solveRBA`:240) re-designed TPU-first: instead of an in-place double loop with
`isw/jsw` checkerboard strides, each half-sweep is a masked, fully-vectorized
update over the whole interior — XLA fuses the 5-point stencil, the mask apply,
and the residual reduction into one pass over the array. The checkerboard mask
replaces control flow (TPUs want branch-free inner loops), and the two
half-sweeps (red = (i+j) even, black = odd, 1-based interior indices — the
exact cells the reference's stride-2 loops visit) preserve the Gauss-Seidel
dependency structure: the black pass sees the red pass's updated values.

Arrays are (jmax+2, imax+2), layout [j, i] — j rows, i contiguous (lane dim).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def checkerboard_mask(jmax: int, imax: int, parity: int, dtype) -> jnp.ndarray:
    """Interior-cell mask (jmax, imax): 1 where (i + j) % 2 == parity.

    i, j are the reference's 1-based interior indices. parity=0 is the "red"
    pass (the reference's first pass: jsw=1 ⇒ visits i+j even), parity=1 black.
    """
    jj = jnp.arange(1, jmax + 1, dtype=jnp.int32)[:, None]
    ii = jnp.arange(1, imax + 1, dtype=jnp.int32)[None, :]
    return (((ii + jj) % 2) == parity).astype(dtype)


def _interior_residual(p, rhs, idx2, idy2):
    """Pointwise residual r = rhs - lap(p) on the interior (jmax, imax)."""
    lap = (p[1:-1, 2:] - 2.0 * p[1:-1, 1:-1] + p[1:-1, :-2]) * idx2 + (
        p[2:, 1:-1] - 2.0 * p[1:-1, 1:-1] + p[:-2, 1:-1]
    ) * idy2
    return rhs[1:-1, 1:-1] - lap


def sor_pass(p, rhs, mask, factor, idx2, idy2):
    """One masked half-sweep. Returns (updated p, sum of masked r²).

    Matches the arithmetic of the reference's per-cell body
    (assignment-4/src/solver.c:205-212): r = rhs - lap(p); p -= factor*r;
    res += r*r — restricted to `mask` cells.
    """
    r = _interior_residual(p, rhs, idx2, idy2) * mask
    p = p.at[1:-1, 1:-1].add(-factor * r)
    return p, jnp.sum(r * r)


def lex_sweep(p, rhs, factor, idx2, idy2):
    """One lexicographic Gauss-Seidel SOR sweep — the reference's `solve`
    (assignment-4/src/solver.c:126-176): j-outer/i-inner, in-place, each cell
    seeing the already-updated west and south neighbours.

    TPU-legal formulation: the in-place double loop is a `lax.scan` over rows
    (carry = the updated row below), and the within-row west dependency is the
    first-order affine recurrence

        p̂_i = c_i + m·p̂_{i-1},   m = factor·idx2,
        c_i = p_i - factor·s_i,
        s_i = rhs_i - [(p_{i+1} - 2p_i)·idx2 + (p̂below_i - 2p_i + pabove_i)·idy2]

    solved with `associative_scan` (log-depth, vector-width work) instead of a
    serial i-loop. The dependency structure — hence the iterate sequence and
    iteration count — is the reference's exactly; only the floating-point
    association inside the scan differs (rounding-level).

    Returns (updated p incl. unchanged ghosts, sum of squared residuals), with
    r_i recovered exactly in recurrence terms as r_i = s_i - idx2·p̂_{i-1}.
    """
    m = factor * idx2

    def combine(lo, hi):
        a1, b1 = lo
        a2, b2 = hi
        return a1 * a2, b2 + a2 * b1

    def row_step(row_below, inputs):
        row, row_above, rhs_row = inputs
        s = rhs_row[1:-1] - (
            (row[2:] - 2.0 * row[1:-1]) * idx2
            + (row_below[1:-1] - 2.0 * row[1:-1] + row_above[1:-1]) * idy2
        )
        c = row[1:-1] - factor * s
        # fold the left-ghost start value into element 0 so the scan output
        # IS p̂ (a_0 = 0 kills the dependence on anything before the row)
        a = jnp.full_like(c, m).at[0].set(0.0)
        b = c.at[0].add(m * row[0])
        _, x = jax.lax.associative_scan(combine, (a, b))
        r = s - idx2 * jnp.concatenate([row[:1], x[:-1]])
        new_row = jnp.concatenate([row[:1], x, row[-1:]])
        return new_row, (new_row, jnp.sum(r * r))

    _, (rows, row_res) = jax.lax.scan(
        row_step, p[0], (p[1:-1], p[2:], rhs[1:-1])
    )
    p = p.at[1:-1].set(rows)
    return p, jnp.sum(row_res)


def residual_all(p, rhs, idx2, idy2):
    """Unmasked interior residual sum-of-squares (diagnostic)."""
    r = _interior_residual(p, rhs, idx2, idy2)
    return jnp.sum(r * r)


def neumann_bc(p):
    """Homogeneous-Neumann ghost copy on all four walls, corners untouched
    (parity: assignment-4/src/solver.c:157-165 — loops run 1..imax/1..jmax,
    so corner ghosts keep their init values; replicated for bitwise output
    parity of the full-array p.dat writer)."""
    p = p.at[0, 1:-1].set(p[1, 1:-1])
    p = p.at[-1, 1:-1].set(p[-2, 1:-1])
    p = p.at[1:-1, 0].set(p[1:-1, 1])
    p = p.at[1:-1, -1].set(p[1:-1, -2])
    return p
