"""Geometric multigrid for the pressure-Poisson equation — a beyond-parity
solver the reference does not have.

The reference's only elliptic solver is SOR/red-black SOR
(/root/reference/assignment-4/src/solver.c:126-296,
assignment-5/sequential/src/solver.c:140-191): O(N^1.17) iterations at the
optimal ω, and every iteration is a full HBM sweep. Geometric multigrid
converges in O(1) V-cycles independent of grid size — on a 128³ NS-3D
pressure solve that replaces hundreds of SOR sweeps per timestep with a
handful of cycles. It is OPT-IN (`tpu_solver mg` in the .par file; default
remains `sor` for trajectory parity with the reference): the converged
answer agrees to the same eps-residual criterion, but the iteration
trajectory is different by construction, so golden-trajectory tests keep
using SOR.

TPU-first design:
- Cell-centered grids (the staggered-pressure layout): coarsening halves
  each interior extent; full-weighting restriction = 2^d-cell mean
  (a reshape-mean, one fused XLA pass), prolongation = piecewise-constant
  injection (`jnp.repeat`), the standard cell-centered pair.
- Smoother: red-black Gauss-Seidel (ω=1) — the same masked half-sweep
  arithmetic as ops/sor.sor_pass / models/ns3d.sor_pass_3d, so the smoother
  inherits the branch-free checkerboard discipline and XLA fusion.
- The V-cycle recursion unrolls at trace time (levels are static), so one
  jitted call executes the whole cycle; the outer convergence loop is the
  same `lax.while_loop` + residual-normalization contract as the SOR solves
  (res = Σr²/(imax·jmax[·kmax]) vs eps², `it` counts V-cycles).
- All-Neumann pressure BCs at every level (ghost copies, walls only). The
  system is singular (constants in the nullspace) exactly as in the
  reference's solver; the smoother leaves the nullspace component untouched
  and convergence is on the residual, matching the SOR semantics. The exact
  DCT bottoms of the PLAIN plans are ADDITIVE residual corrections
  (p += zero-mean e), so the nullspace survives even when a small grid's
  plan is a single level — the solve stays exact in one cycle without
  resetting the mean. (The obstacle plans' dense bottoms replace the
  iterate; a single-level obstacle plan resets the mean, fft-like.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import flags as _flags

from .sor import _interior_residual


def mg_levels(*extents, min_size: int = 4):
    """Level plan: halve every interior extent while all stay even and at
    least 2·min_size; level 0 is the fine grid."""
    levels = [tuple(extents)]
    while all(d % 2 == 0 and d >= 2 * min_size for d in levels[-1]):
        levels.append(tuple(d // 2 for d in levels[-1]))
    return levels


# The DCT bottom solve is EXACT at any size (a few MXU matmuls), so plain
# grids stop coarsening once a level fits this budget — each extra tiny
# level below it buys nothing and costs a chain of launch-bound small ops
# (the same lesson the obstacle bottom taught at 59x: see
# _DENSE_BOTTOM_MAX_CELLS). 65536 = 256^2: the DCT matmuls there are
# negligible next to one fine-grid sweep.
_DCT_BOTTOM_MAX_CELLS = 65536


def _truncate_levels(levels, max_cells, scale: int = 1):
    """Cut the level plan at the first level whose cell count (×scale — the
    mesh size for distributed plans, where levels carry LOCAL extents but
    the bottom is solved globally) fits the bottom budget. A plan may be a
    single level (grids under the budget): the PLAIN plans' DCT bottoms are
    ADDITIVE residual corrections, so even then the incoming iterate's
    mean/nullspace component survives. The OBSTACLE plans' dense bottoms
    replace the iterate instead — a single-level obstacle plan (grid at or
    under _DENSE_BOTTOM_MAX_CELLS) resets the mean, fft-like."""
    import math

    for idx, ext in enumerate(levels):
        if math.prod(ext) * scale <= max_cells:
            return levels[: idx + 1]
    return levels


# Relative-change stall tolerance for the MG convergence loops. Some
# production solves CANNOT reach eps: the canal configs' outflow BCs make
# the Neumann RHS inconsistent, so the residual floors at the inconsistency
# (the reference's own canal solves are itermax-capped for the same reason,
# tests/test_ns2d.py), and f32 runs floor at round-off. SOR creeps toward
# such floors slowly enough that capping is the only option, but a V-cycle
# CONTRACTS by ~10x per cycle until the floor and then flatlines — so a
# stalled residual IS convergence-to-floor, and burning the remaining
# itermax cycles (500 cycles x ~2 ms at 2048x512) is pure waste. The loop
# stops when the residual changed less than MG_STALL_RTOL relative over one
# cycle; a genuinely converging cycle changes it ~10x, so the detector
# cannot mistake progress for a stall. Overridable per run via the .par key
# `tpu_mg_stall_rtol` (0 disables the detector entirely — itermax-parity
# with the reference's capped solves — every make_*_mg_solve factory takes
# the value as `stall_rtol`).
MG_STALL_RTOL = 1e-4


def _stalled(prev, res, it, rtol=MG_STALL_RTOL):
    """The stall predicate — single home; the single-device and distributed
    loops share it so their stopping contracts cannot drift. `rtol` is
    static at trace time; rtol<=0 compiles the detector away; None means
    the module default (callers plumbing a .par key pass it verbatim)."""
    if rtol is None:
        rtol = MG_STALL_RTOL
    if rtol <= 0:
        return jnp.full((), False)
    return jnp.logical_and(
        it >= 2, jnp.abs(prev - res) <= rtol * res
    )


def _mg_converge_loop(vcycle, residual_of, norm, eps, itermax, dtype,
                      stall_rtol=MG_STALL_RTOL):
    """The shared MG convergence loop: `(p, rhs) -> (p, res, it)` with the
    SOR solve contract PLUS the stall detector above — a solve may return
    res > eps² before itermax when the residual flatlines (stall_rtol
    relative change per cycle; <=0 disables). `residual_of(p, rhs)`
    returns the interior residual array of the fine level."""
    epssq = eps * eps

    def solve(p, rhs):
        def cond(c):
            p, res, prev, it = c
            return jnp.logical_and(
                jnp.logical_and(res >= epssq, it < itermax),
                jnp.logical_not(_stalled(prev, res, it, stall_rtol)),
            )

        def body(c):
            p, prev_res, _, it = c
            p = vcycle(p, rhs)
            r = residual_of(p, rhs)
            res = jnp.sum(r * r) / norm
            if _flags.debug():
                jax.debug.print("{} Residuum: {}", it, res)  # it = V-cycle
            return p, res, prev_res, it + 1

        p, res, _, it = lax.while_loop(
            cond, body,
            (p, jnp.asarray(1.0, dtype), jnp.asarray(jnp.inf, dtype),
             jnp.asarray(0, jnp.int32)),
        )
        return p, res, it

    return solve


# ----------------------------------------------------------------------
# 2-D components (arrays are extended (j+2, i+2), ghosts included)
# ----------------------------------------------------------------------


def _neumann2(p):
    p = p.at[0, 1:-1].set(p[1, 1:-1])
    p = p.at[-1, 1:-1].set(p[-2, 1:-1])
    p = p.at[1:-1, 0].set(p[1:-1, 1])
    p = p.at[1:-1, -1].set(p[1:-1, -2])
    return p


def _residual2(p, rhs, idx2, idy2):
    return _interior_residual(p, rhs, idx2, idy2)


# Smoothing is ALWAYS unrolled at trace time (n is a small static count).
# A lax.fori_loop variant for large coarse-solve iteration counts was tried
# and correlated with TPU device faults (UNAVAILABLE class) when nested
# inside the solve while_loop inside the NS chunk while_loop; later
# investigation showed the fault class is partly TRANSIENT infra flakiness
# on large programs (models/_driver._is_transient_device_fault), so the
# causal story is uncertain — but the unrolled form is simpler and the
# coarse level needs no iteration at all now: it is solved exactly by DCT
# diagonalization (ops/dctpoisson.py).


def _smooth2(p, rhs, masks, factor, idx2, idy2, n):
    """n red-black Gauss-Seidel iterations (sor_pass arithmetic, ω baked
    into factor) + Neumann refresh each."""
    red, black = masks
    for _ in range(n):
        r = _residual2(p, rhs, idx2, idy2) * red
        p = p.at[1:-1, 1:-1].add(-factor * r)
        r = _residual2(p, rhs, idx2, idy2) * black
        p = p.at[1:-1, 1:-1].add(-factor * r)
        p = _neumann2(p)
    return p


# Fine-level smoothing dominates MG cost (round-3 measurement: plain MG at
# 4096^2 f32 322.7 ms/step, obstacle MG at 2048x512 90.9 — jnp sweeps), so
# levels at least this many interior cells dispatch the temporal-blocked
# Pallas kernel instead (same arithmetic, n sweeps per HBM round trip).
# Below it the jnp sweeps are already cheap and the kernel's pad/unpad
# envelope would dominate.
_PALLAS_SMOOTH_MIN_CELLS = 512 * 256


def _pallas_smoother_2d(il, jl, dxl, dyl, dtype, n, fluid=None,
                        backend="auto"):
    """Build `smooth(p_ext, rhs_ext) -> p_ext`: n ω=1 red-black sweeps via
    the temporal-blocked Pallas kernel (ops/sor_pallas.make_rb_iter_tblock;
    fluid!=None switches to the flag-masked obstacle stencil) — the same
    per-iteration arithmetic as _smooth2 / sor_pass_obstacle with the
    Neumann refresh fused. Returns None whenever ineligible (no TPU, wide
    dtype, VMEM-infeasible, or a level too small to pay the pad/unpad
    envelope) — callers keep the jnp sweeps then. backend="pallas" forces
    (interpret off-TPU: the test mode) and skips the size threshold."""
    from ..models.poisson import _use_pallas

    if n < 1 or not _use_pallas(backend, dtype):
        return None
    if backend != "pallas" and il * jl < _PALLAS_SMOOTH_MIN_CELLS:
        return None
    from . import sor_pallas as sp

    try:
        # interpret resolves inside the maker (real kernel on TPU,
        # interpret elsewhere — the forced-backend test mode)
        rb, br, h = sp.make_rb_iter_tblock(
            il, jl, dxl, dyl, 1.0, dtype, n_inner=n, fluid=fluid,
        )
    except ValueError:
        return None
    if rb is None:
        return None

    def smooth(p, rhs):
        pp, _ = rb(sp.pad_array(p, br, h), sp.pad_array(rhs, br, h))
        return sp.unpad_array(pp, jl, il, h)

    return smooth


def _restrict2(r):
    """Full-weighting for cell-centered grids: mean of each 2x2 block."""
    J, I = r.shape
    return r.reshape(J // 2, 2, I // 2, 2).mean(axis=(1, 3))


def _prolong2(e):
    """Piecewise-constant injection: each coarse cell covers its 2x2 fine
    block."""
    return jnp.repeat(jnp.repeat(e, 2, axis=0), 2, axis=1)


def _embed2(interior):
    J, I = interior.shape
    return jnp.zeros((J + 2, I + 2), interior.dtype).at[1:-1, 1:-1].set(interior)


def _resolve_fused_solo(levels, dtype, fused, backend, key):
    """`tpu_mg_fused` gate for one single-device MG build: plan feasibility
    via ops/mg_fused.plan_why_not (single-level plans, VMEM-infeasible
    stacks, missing backend), decision recorded under `key` by
    utils/dispatch.resolve_mg_fused; a positive decision re-records with
    the launch/level census the jaxpr contract pins — the whole V-cycle is
    exactly TWO Pallas launches regardless of depth (DOWN and UP, with the
    exact jnp bottom between them)."""
    from ..utils import dispatch as _dispatch
    from . import mg_fused as mf

    use = _dispatch.resolve_mg_fused(
        fused, backend, dtype, key,
        why_not=mf.plan_why_not(levels, dtype),
        probe=mf.probe_mg_fused,
    )
    if use:
        _dispatch.record(
            key, f"pallas_fused_cycle (launches=2, levels={len(levels)})"
        )
    return use


# FFT-preconditioned Richardson iterations for obstacle bottoms the dense
# pinv cannot afford (tpu_mg_fused on). Each pass corrects by the
# constant-coefficient DCT solve of the obstacle residual — exact away from
# the obstacle, where the operator IS constant-coefficient — then polishes
# the boundary-layer error with one red-black ω=1 sweep. A handful of MXU
# matmul rounds replaces the n_coarse=60 smooth-to-death unroll (~300
# launch-bound tiny ops) that over-budget plans historically fell back to.
_FFT_COARSE_ITERS = 4


def _make_fft_coarse_2d(m, dxl, dyl, idx2, idy2, red, black,
                        n_rich: int = _FFT_COARSE_ITERS):
    """`apply(p_ext, rhs_ext) -> p_ext` — the 2-D FFT-preconditioned coarse
    application (see _FFT_COARSE_ITERS). `m` is the bottom level's
    ObstacleMasks at ω=1; `red`/`black` its checkerboards."""
    from .dctpoisson import poisson_dct_2d
    from .obstacle import obstacle_residual, sor_pass_obstacle

    def apply(p, rhs):
        for _ in range(n_rich):
            r = obstacle_residual(p, rhs, m, idx2, idy2)
            e = poisson_dct_2d(r, dxl, dyl)
            p = _neumann2(p.at[1:-1, 1:-1].add(e * m.p_mask))
            p, _ = sor_pass_obstacle(p, rhs, red, m, idx2, idy2)
            p, _ = sor_pass_obstacle(p, rhs, black, m, idx2, idy2)
            p = _neumann2(p)
        return p

    return apply


def _make_fft_coarse_3d(m, dxl, dyl, dzl, idx2, idy2, idz2, odd, even,
                        n_rich: int = _FFT_COARSE_ITERS):
    """3-D twin of _make_fft_coarse_2d (odd-then-even sweep order, the 3-D
    obstacle solver convention)."""
    from ..models.ns3d import neumann_faces_3d
    from .dctpoisson import poisson_dct_3d
    from .obstacle3d import obstacle_residual_3d, sor_pass_obstacle_3d

    def apply(p, rhs):
        for _ in range(n_rich):
            r = obstacle_residual_3d(p, rhs, m, idx2, idy2, idz2)
            e = poisson_dct_3d(r, dxl, dyl, dzl)
            p = neumann_faces_3d(
                p.at[1:-1, 1:-1, 1:-1].add(e * m.p_mask)
            )
            p, _ = sor_pass_obstacle_3d(p, rhs, odd, m, idx2, idy2, idz2)
            p, _ = sor_pass_obstacle_3d(p, rhs, even, m, idx2, idy2, idz2)
            p = neumann_faces_3d(p)
        return p

    return apply


def make_mg_vcycle_2d(imax, jmax, dx, dy, dtype,
                      n_pre: int = 2, n_post: int = 2,
                      backend: str = "auto", fused: str = "off"):
    """Build `vcycle(p_ext, rhs_ext) -> p_ext` on the fine extended grid.
    Level geometry doubles the spacing each coarsening (cell-centered).
    The coarsest level is solved EXACTLY by DCT diagonalization
    (ops/dctpoisson.py, MXU matmuls) — no unrolled coarse smoothing, and an
    odd-extent bottom grid (e.g. 100² stops at 25²) costs the same handful
    of matmuls as a tiny one. Large levels smooth through the
    temporal-blocked Pallas kernel when eligible (_pallas_smoother_2d: same
    red-black ω=1 arithmetic, n sweeps per HBM round trip); small levels
    and non-TPU runs keep the jnp sweeps.

    `fused` (.par key tpu_mg_fused) dispatches the whole cycle as TWO
    dynamic-extent Pallas launches (ops/mg_fused.py) with this same exact
    DCT bottom between them — the jnp ladder here stays the parity
    oracle."""
    from .dctpoisson import poisson_dct_2d
    from .sor import checkerboard_mask

    levels = _truncate_levels(mg_levels(jmax, imax), _DCT_BOTTOM_MAX_CELLS)
    use_fused = _resolve_fused_solo(levels, dtype, fused, backend,
                                    "mg2d_fused")
    cfg = []
    for lvl, (jl, il) in enumerate(levels):
        dxl, dyl = dx * (2 ** lvl), dy * (2 ** lvl)
        dx2, dy2 = dxl * dxl, dyl * dyl
        cfg.append(
            dict(
                dx=dxl,
                dy=dyl,
                idx2=1.0 / dx2,
                idy2=1.0 / dy2,
                # ω=1 Gauss-Seidel smoothing factor
                factor=0.5 * (dx2 * dy2) / (dx2 + dy2),
                masks=(
                    checkerboard_mask(jl, il, 0, dtype),
                    checkerboard_mask(jl, il, 1, dtype),
                ),
                # fused cycles smooth in-kernel: skip the ladder smoothers
                sm={} if use_fused else {
                    n: _pallas_smoother_2d(il, jl, dxl, dyl, dtype, n,
                                           backend=backend)
                    for n in {n_pre, n_post} if n
                },
            )
        )

    def smooth(p, rhs, lvl, n):
        c = cfg[lvl]
        k = c["sm"].get(n)
        if k is not None:
            return k(p, rhs)
        return _smooth2(p, rhs, c["masks"], c["factor"],
                        c["idx2"], c["idy2"], n)

    def bottom(p, rhs):
        # exact ADDITIVE bottom solve: correct p by the zero-mean DCT
        # solution of its residual equation. For error equations
        # (p = zeros) this equals the direct solve; for a single-level
        # hierarchy it preserves the incoming iterate's mean/nullspace
        # component — the smoother semantics the module contract
        # promises — while staying exact in one application.
        c = cfg[-1]
        r = _residual2(p, rhs, c["idx2"], c["idy2"])
        e = poisson_dct_2d(r, c["dx"], c["dy"])
        return _neumann2(p.at[1:-1, 1:-1].add(e))

    def vcycle(p, rhs, lvl=0):
        c = cfg[lvl]
        if lvl == len(cfg) - 1:
            return bottom(p, rhs)
        p = smooth(p, rhs, lvl, n_pre)
        r = _residual2(p, rhs, c["idx2"], c["idy2"])
        r2 = _restrict2(r)
        e2 = vcycle(_embed2(jnp.zeros_like(r2)), _embed2(r2), lvl + 1)
        p = p.at[1:-1, 1:-1].add(_prolong2(e2[1:-1, 1:-1]))
        p = _neumann2(p)
        return smooth(p, rhs, lvl, n_post)

    if not use_fused:
        return vcycle

    from . import mg_fused as mf

    down, up, plane = mf.make_cycle_kernels(levels, (dx, dy), dtype,
                                            n_pre, n_post)
    jb, ib = levels[-1]

    def vcycle_fused(p, rhs):
        # the whole restrict→smooth→prolong chain in TWO launches; the
        # exact DCT bottom stays a jnp application between them (the
        # coarsest rhs plane's live corner is a static slice, and the
        # recursed ladder always hands the bottom a zero iterate)
        pstk, rstk = down(mf.pad_plane(p, plane), mf.pad_plane(rhs, plane))
        rb = rstk[-1][: jb + 2, : ib + 2]
        pbot = bottom(jnp.zeros_like(rb), rb)
        return mf.unpad_plane(up(pstk, rstk, mf.pad_plane(pbot, plane)),
                              (jmax, imax))

    return vcycle_fused


def make_mg_solve_2d(imax, jmax, dx, dy, eps, itermax, dtype,
                     n_pre: int = 2, n_post: int = 2,
                     stall_rtol=MG_STALL_RTOL, backend: str = "auto",
                     fused: str = "off"):
    """Convergence loop with the SOR solve contract:
    `(p_ext, rhs_ext) -> (p_ext, res, it)` where res = Σr²/(imax·jmax) of
    the state BEFORE the last cycle's smoothing — evaluated fresh per cycle —
    and `it` counts V-cycles. NOTE the contract addition over SOR: the loop
    also stops when the residual stalls (`stall_rtol` relative change per
    cycle, .par key tpu_mg_stall_rtol; 0 restores pure eps/itermax)."""
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax, dtype, f"mg2d {imax}x{jmax}")
    vcycle = make_mg_vcycle_2d(imax, jmax, dx, dy, dtype, n_pre, n_post,
                               backend, fused)
    idx2, idy2 = 1.0 / (dx * dx), 1.0 / (dy * dy)
    return _mg_converge_loop(
        vcycle, lambda p, rhs: _residual2(p, rhs, idx2, idy2),
        float(imax * jmax), eps, itermax, dtype, stall_rtol,
    )


# ----------------------------------------------------------------------
# 3-D components (arrays are extended (k+2, j+2, i+2))
# ----------------------------------------------------------------------


def _residual3(p, rhs, idx2, idy2, idz2):
    from ..models.ns3d import interior_residual_3d

    return interior_residual_3d(p, rhs, idx2, idy2, idz2)


def _smooth3(p, rhs, masks, factor, idx2, idy2, idz2, n):
    # always unrolled — see the fori_loop TPU-fault note above _smooth2
    from ..models.ns3d import neumann_faces_3d

    odd, even = masks
    for _ in range(n):
        r = _residual3(p, rhs, idx2, idy2, idz2) * odd
        p = p.at[1:-1, 1:-1, 1:-1].add(-factor * r)
        r = _residual3(p, rhs, idx2, idy2, idz2) * even
        p = p.at[1:-1, 1:-1, 1:-1].add(-factor * r)
        p = neumann_faces_3d(p)
    return p


def _restrict3(r):
    K, J, I = r.shape
    return r.reshape(K // 2, 2, J // 2, 2, I // 2, 2).mean(axis=(1, 3, 5))


def _prolong3(e):
    return jnp.repeat(jnp.repeat(jnp.repeat(e, 2, axis=0), 2, axis=1), 2, axis=2)


def _embed3(interior):
    K, J, I = interior.shape
    out = jnp.zeros((K + 2, J + 2, I + 2), interior.dtype)
    return out.at[1:-1, 1:-1, 1:-1].set(interior)


def _pallas_smoother_3d(il, jl, kl, dxl, dyl, dzl, dtype, n, fluid=None,
                        backend="auto"):
    """3-D twin of _pallas_smoother_2d: n ω=1 red-black sweeps via the
    temporal-blocked 3-D kernel (ops/sor3d_pallas.make_rb_iter_tblock_3d;
    fluid!=None switches to the flag-masked obstacle stencil). Returns None
    whenever ineligible — callers keep the jnp sweeps then."""
    from ..models.ns3d import _use_pallas_3d

    if n < 1 or not _use_pallas_3d(backend, dtype):
        return None
    if backend != "pallas" and il * jl * kl < _PALLAS_SMOOTH_MIN_CELLS:
        return None
    import numpy as np

    from . import sor3d_pallas as sp3

    masked = fluid is not None
    bk = sp3.pick_block_k(kl, jl, il, dtype, n, masked=masked)
    if backend != "pallas" and sp3.block_k_degenerate(bk, kl, n):
        return None
    try:
        # pass the checked block depth through so the degeneracy guard and
        # the kernel can never validate different values
        rb, bk = sp3.make_rb_iter_tblock_3d(
            il, jl, kl, dxl, dyl, dzl, 1.0, dtype, n_inner=n, block_k=bk,
            fluid=None if fluid is None else np.asarray(fluid),
        )
    except ValueError:
        return None
    if rb is None:
        return None

    def smooth(p, rhs):
        pp, _ = rb(sp3.pad_array_3d(p, bk, n), sp3.pad_array_3d(rhs, bk, n))
        return sp3.unpad_array_3d(pp, kl, jl, il, n)

    return smooth


def make_mg_vcycle_3d(imax, jmax, kmax, dx, dy, dz, dtype,
                      n_pre: int = 2, n_post: int = 2,
                      backend: str = "auto", fused: str = "off"):
    """3-D twin of make_mg_vcycle_2d (exact DCT bottom solve; large levels
    smooth through the temporal-blocked 3-D Pallas kernel when eligible;
    `fused` dispatches the two-launch cycle of ops/mg_fused.py)."""
    from ..models.ns3d import checkerboard_mask_3d, neumann_faces_3d
    from .dctpoisson import poisson_dct_3d

    levels = _truncate_levels(mg_levels(kmax, jmax, imax),
                              _DCT_BOTTOM_MAX_CELLS)
    use_fused = _resolve_fused_solo(levels, dtype, fused, backend,
                                    "mg3d_fused")
    cfg = []
    for lvl, (kl, jl, il) in enumerate(levels):
        dxl, dyl, dzl = dx * (2 ** lvl), dy * (2 ** lvl), dz * (2 ** lvl)
        dx2, dy2, dz2 = dxl * dxl, dyl * dyl, dzl * dzl
        cfg.append(
            dict(
                dx=dxl,
                dy=dyl,
                dz=dzl,
                idx2=1.0 / dx2,
                idy2=1.0 / dy2,
                idz2=1.0 / dz2,
                factor=0.5 * (dx2 * dy2 * dz2)
                / (dy2 * dz2 + dx2 * dz2 + dx2 * dy2),
                masks=(
                    checkerboard_mask_3d(kl, jl, il, 1, dtype),
                    checkerboard_mask_3d(kl, jl, il, 0, dtype),
                ),
                # fused cycles smooth in-kernel: skip the ladder smoothers
                sm={} if use_fused else {
                    n: _pallas_smoother_3d(il, jl, kl, dxl, dyl, dzl,
                                           dtype, n, backend=backend)
                    for n in {n_pre, n_post} if n
                },
            )
        )

    def smooth(p, rhs, lvl, n):
        c = cfg[lvl]
        k = c["sm"].get(n)
        if k is not None:
            return k(p, rhs)
        return _smooth3(p, rhs, c["masks"], c["factor"],
                        c["idx2"], c["idy2"], c["idz2"], n)

    def bottom(p, rhs):
        # exact ADDITIVE bottom solve — see the 2-D twin's rationale
        c = cfg[-1]
        r = _residual3(p, rhs, c["idx2"], c["idy2"], c["idz2"])
        e = poisson_dct_3d(r, c["dx"], c["dy"], c["dz"])
        return neumann_faces_3d(p.at[1:-1, 1:-1, 1:-1].add(e))

    def vcycle(p, rhs, lvl=0):
        c = cfg[lvl]
        if lvl == len(cfg) - 1:
            return bottom(p, rhs)
        p = smooth(p, rhs, lvl, n_pre)
        r = _residual3(p, rhs, c["idx2"], c["idy2"], c["idz2"])
        r2 = _restrict3(r)
        e2 = vcycle(_embed3(jnp.zeros_like(r2)), _embed3(r2), lvl + 1)
        p = p.at[1:-1, 1:-1, 1:-1].add(_prolong3(e2[1:-1, 1:-1, 1:-1]))
        p = neumann_faces_3d(p)
        return smooth(p, rhs, lvl, n_post)

    if not use_fused:
        return vcycle

    from . import mg_fused as mf

    down, up, plane = mf.make_cycle_kernels(levels, (dx, dy, dz), dtype,
                                            n_pre, n_post)
    kb, jb, ib = levels[-1]

    def vcycle_fused(p, rhs):
        # two launches + the exact jnp DCT bottom — see the 2-D twin
        pstk, rstk = down(mf.pad_plane(p, plane), mf.pad_plane(rhs, plane))
        rb = rstk[-1][: kb + 2, : jb + 2, : ib + 2]
        pbot = bottom(jnp.zeros_like(rb), rb)
        return mf.unpad_plane(up(pstk, rstk, mf.pad_plane(pbot, plane)),
                              (kmax, jmax, imax))

    return vcycle_fused


def make_mg_solve_3d(imax, jmax, kmax, dx, dy, dz, eps, itermax, dtype,
                     n_pre: int = 2, n_post: int = 2,
                     stall_rtol=MG_STALL_RTOL, backend: str = "auto",
                     fused: str = "off"):
    """3-D twin of make_mg_solve_2d (same solve contract as
    models/ns3d.make_pressure_solve_3d; `it` counts V-cycles; stalls stop
    the loop early per `stall_rtol` — see make_mg_solve_2d)."""
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax * kmax, dtype,
                    f"mg3d {imax}x{jmax}x{kmax}")
    vcycle = make_mg_vcycle_3d(imax, jmax, kmax, dx, dy, dz, dtype,
                               n_pre, n_post, backend, fused)
    idx2 = 1.0 / (dx * dx)
    idy2 = 1.0 / (dy * dy)
    idz2 = 1.0 / (dz * dz)
    return _mg_converge_loop(
        vcycle, lambda p, rhs: _residual3(p, rhs, idx2, idy2, idz2),
        float(imax * jmax * kmax), eps, itermax, dtype, stall_rtol,
    )


# ----------------------------------------------------------------------
# Obstacle multigrid (2-D): the O(1)-cycles solver for the flag-masked
# configs where the DCT direct solve is unavailable (non-constant
# coefficients). Geometry coarsens by fluid-ANY (a coarse cell is fluid if
# any of its 2x2 fine cells is), and every level REDISCRETIZES the obstacle
# operator from its own flag field (ops/obstacle.make_masks with ω=1), so
# smoothing, residual, and the bottom solve all run the same per-direction
# eps-coefficient stencil as the fine-level SOR solver. The bottom level has
# no DCT (obstacles!), so it is smoothed to death with an unrolled sweep
# block — at the bottom extents (≤ ~2·min_size per axis) that is cheap and
# exact enough for the V-cycle contract.
# ----------------------------------------------------------------------


def coarsen_fluid(fluid: "np.ndarray") -> "np.ndarray":
    """(J+2, I+2) bool fluid flags -> coarse (J/2+2, I/2+2): interior cell
    fluid iff ANY of its 2x2 fine cells is fluid (keeps narrow channels
    open — the conservative choice for convergence near blocky obstacles);
    the ghost ring stays fluid like the fine level's."""
    import numpy as np

    fi = fluid[1:-1, 1:-1]
    J, I = fi.shape
    blocks = fi.reshape(J // 2, 2, I // 2, 2)
    ci = blocks.any(axis=(1, 3))
    out = np.ones((J // 2 + 2, I // 2 + 2), dtype=bool)
    out[1:-1, 1:-1] = ci
    return out


def _obstacle_residual(p, rhs, m, idx2, idy2):
    """The shared eps-coefficient residual (ops/obstacle.obstacle_residual —
    one home for the stencil, the smoother updates with the same values)."""
    from .obstacle import obstacle_residual

    return obstacle_residual(p, rhs, m, idx2, idy2)


# The obstacle MG bottom is solved EXACTLY by a dense pseudo-inverse (the
# obstacle analog of the uniform MG's DCT bottom — obstacles rule the DCT
# out, but at the coarsest extents the eps-coefficient operator is a small
# matrix). Levels stop coarsening once a level fits this budget: measured on
# v5e at canal_obstacle 2048x512, the previous smooth-to-death bottom (60
# unrolled sweeps on a 4x16 grid = ~300 launch-bound tiny ops) cost 3.5 of
# the 5.7 ms/cycle; the pinv matmul replaces it outright, and stopping at
# <=1024 cells also trims the deepest tiny-op hierarchy levels. pinv cost
# is trace-time-only (N^3 at N<=1024: seconds, once).
_DENSE_BOTTOM_MAX_CELLS = 1024


def _dense_obstacle_bottom(fluid, dxl, dyl, dtype):
    """Trace-time pinv of the eps-coefficient all-Neumann operator on the
    (small) bottom grid: returns `solve_exact(rhs_ext) -> e_ext` computing
    lap(e) = rhs on fluid cells, e = 0 on obstacle cells, via one matmul.
    Wall ghosts drop out (Neumann cancels the term — p_ghost = p_edge);
    the singular all-Neumann system takes the pinv's minimum-norm answer
    (constants-per-component nullspace, same semantics as the smoothed
    bottom it replaces)."""
    import numpy as np

    fl = np.asarray(fluid)[1:-1, 1:-1].astype(bool)
    J, I = fl.shape
    N = J * I
    idx2, idy2 = 1.0 / (dxl * dxl), 1.0 / (dyl * dyl)
    A = np.zeros((N, N))

    def k(j, i):
        return j * I + i

    for j in range(J):
        for i in range(I):
            kk = k(j, i)
            if not fl[j, i]:
                A[kk, kk] = 1.0  # obstacle cell: e stays 0 (rhs is 0 there)
                continue
            for dj, di, w in ((0, 1, idx2), (0, -1, idx2),
                              (1, 0, idy2), (-1, 0, idy2)):
                jj, ii = j + dj, i + di
                if not (0 <= jj < J and 0 <= ii < I):
                    continue  # wall ghost: the Neumann mirror cancels it
                if not fl[jj, ii]:
                    continue  # obstacle neighbour: eps coefficient is 0
                A[kk, k(jj, ii)] += w
                A[kk, kk] -= w
    Apinv = jnp.asarray(np.linalg.pinv(A), dtype)
    # zero the obstacle COLUMNS of the input: the identity rows would
    # otherwise copy any nonzero rhs at obstacle cells straight into e
    # (restricted residuals are masked to 0 there, but a single-level plan
    # hands this solver the caller's RAW rhs)
    fl_mask = jnp.asarray(fl.reshape(-1), dtype)

    def solve_exact(p, rhs):
        e = (Apinv @ (rhs[1:-1, 1:-1].reshape(-1) * fl_mask)).reshape(J, I)
        # the incoming iterate is irrelevant — the direct solution replaces
        # it (constants aside), exactly like the uniform MG's DCT bottom
        return _neumann2(jnp.zeros_like(p).at[1:-1, 1:-1].set(e))

    return solve_exact


def make_obstacle_mg_solve_2d(imax, jmax, dx, dy, eps, itermax, masks, dtype,
                              n_pre: int = 2, n_post: int = 2,
                              n_coarse: int = 60,
                              stall_rtol=MG_STALL_RTOL,
                              backend: str = "auto", fused: str = "off"):
    """Obstacle-capable MG convergence loop:
    `(p_ext, rhs_ext) -> (p_ext, res, it)`, `it` counting V-cycles, residual
    normalized by the FLUID cell count (the contract of
    ops/obstacle.make_obstacle_solver_fn). `masks` is the fine-level
    ObstacleMasks built with the run's ω — smoothing rebuilds every level at
    ω=1 from the coarsened flags, and large levels dispatch the flag-masked
    temporal-blocked Pallas kernel (_pallas_smoother_2d — the round-3
    obstacle headline kernel, now also the MG smoother). The bottom level
    is solved EXACTLY by the dense pinv (_dense_obstacle_bottom) and the
    level plan stops at _DENSE_BOTTOM_MAX_CELLS; `n_coarse` smoothing is
    the fallback only when the pinv is unavailable. Stalled residuals
    stop the loop early per `stall_rtol` — see make_mg_solve_2d."""
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax, dtype,
                    f"mg2d_obstacle {imax}x{jmax}")
    import numpy as np

    from .obstacle import make_masks
    from .sor import checkerboard_mask

    # the dense bottom replaces coarsening below its budget: a bigger exact
    # bottom AND fewer launch-bound tiny levels (the 60-sweep smoothed
    # bottom was 3.5 of 5.7 ms/cycle at 2048x512 — ~300 tiny ops)
    levels = _truncate_levels(mg_levels(jmax, imax),
                              _DENSE_BOTTOM_MAX_CELLS)
    use_fused = _resolve_fused_solo(levels, dtype, fused, backend,
                                    "mg2d_obstacle_fused")
    fine_fluid = np.asarray(masks.fluid).astype(bool)
    cfg = []
    fluid = fine_fluid
    for lvl, (jl, il) in enumerate(levels):
        dxl, dyl = dx * (2 ** lvl), dy * (2 ** lvl)
        if lvl > 0:
            fluid = coarsen_fluid(fluid)
        cfg.append(
            dict(
                m=make_masks(fluid, dxl, dyl, 1.0, dtype),  # ω=1 smoother
                idx2=1.0 / (dxl * dxl),
                idy2=1.0 / (dyl * dyl),
                red=checkerboard_mask(jl, il, 0, dtype),
                black=checkerboard_mask(jl, il, 1, dtype),
                # fused cycles smooth in-kernel: skip the ladder smoothers
                sm={} if use_fused else {
                    n: _pallas_smoother_2d(il, jl, dxl, dyl, dtype, n,
                                           fluid=fluid, backend=backend)
                    for n in {n_pre, n_post} if n
                },
            )
        )

    from .obstacle import sor_pass_obstacle

    jl_b, il_b = levels[-1]
    lvl_b = len(levels) - 1
    bottom_exact = (
        _dense_obstacle_bottom(
            cfg[-1]["m"].fluid, dx * 2 ** lvl_b, dy * 2 ** lvl_b, dtype,
        )
        if jl_b * il_b <= _DENSE_BOTTOM_MAX_CELLS
        else None  # plan could not coarsen into budget: smoothed fallback
    )
    bottom_fft = None
    if bottom_exact is None and fused == "on":
        # tpu_mg_fused on: over-budget bottoms (the plan could not coarsen
        # into the pinv budget — ragged/odd extents stall coarsening early)
        # apply the FFT-preconditioned Richardson rounds instead of the
        # n_coarse smooth-to-death unroll
        from ..utils import dispatch as _dispatch

        cb = cfg[-1]
        bottom_fft = _make_fft_coarse_2d(
            cb["m"], dx * 2 ** lvl_b, dy * 2 ** lvl_b,
            cb["idx2"], cb["idy2"], cb["red"], cb["black"],
        )
        _dispatch.record("mg2d_obstacle_coarse",
                         f"fft_richardson (n={_FFT_COARSE_ITERS})")

    def smooth(p, rhs, lvl, n):
        c = cfg[lvl]
        k = c["sm"].get(n)
        if k is not None:
            return k(p, rhs)
        for _ in range(n):
            p, _ = sor_pass_obstacle(
                p, rhs, c["red"], c["m"], c["idx2"], c["idy2"]
            )
            p, _ = sor_pass_obstacle(
                p, rhs, c["black"], c["m"], c["idx2"], c["idy2"]
            )
            p = _neumann2(p)
        return p

    def bottom(p, rhs):
        if bottom_exact is not None:
            return bottom_exact(p, rhs)
        if bottom_fft is not None:
            return bottom_fft(p, rhs)
        return smooth(p, rhs, len(cfg) - 1, n_coarse)

    def vcycle(p, rhs, lvl=0):
        c = cfg[lvl]
        if lvl == len(cfg) - 1:
            return bottom(p, rhs)
        p = smooth(p, rhs, lvl, n_pre)
        r = _obstacle_residual(p, rhs, c["m"], c["idx2"], c["idy2"])
        r2 = _restrict2(r)
        e2 = vcycle(_embed2(jnp.zeros_like(r2)), _embed2(r2), lvl + 1)
        # inject into fluid cells only (obstacle cells stay untouched)
        p = p.at[1:-1, 1:-1].add(_prolong2(e2[1:-1, 1:-1]) * c["m"].p_mask)
        p = _neumann2(p)
        return smooth(p, rhs, lvl, n_post)

    cycle = vcycle
    if use_fused:
        from . import mg_fused as mf

        down, up, plane = mf.make_cycle_kernels(
            levels, (dx, dy), dtype, n_pre, n_post,
            # per-level flags + ω=1 factors baked verbatim so the kernel
            # relaxes with bitwise the ladder's precomputed coefficients
            fluid_levels=[np.asarray(c["m"].fluid) for c in cfg],
            factor_levels=[c["m"].factor for c in cfg],
        )
        jb_f, ib_f = levels[-1]

        def vcycle_fused(p, rhs):
            # two launches + the exact jnp bottom (dense pinv, or the FFT
            # Richardson rounds when over budget) — see make_mg_vcycle_2d
            pstk, rstk = down(mf.pad_plane(p, plane),
                              mf.pad_plane(rhs, plane))
            rb = rstk[-1][: jb_f + 2, : ib_f + 2]
            pbot = bottom(jnp.zeros_like(rb), rb)
            return mf.unpad_plane(
                up(pstk, rstk, mf.pad_plane(pbot, plane)), (jmax, imax)
            )

        cycle = vcycle_fused

    fine = cfg[0]
    return _mg_converge_loop(
        cycle,
        lambda p, rhs: _obstacle_residual(
            p, rhs, fine["m"], fine["idx2"], fine["idy2"]
        ),
        float(fine["m"].n_fluid), eps, itermax, dtype, stall_rtol,
    )


# ----------------------------------------------------------------------
# Distributed multigrid: V-cycles over a device mesh (call INSIDE shard_map)
# ----------------------------------------------------------------------
#
# Level plan: coarsen DISTRIBUTED levels while every shard's local extents
# stay even and >= 2*min_size (restriction/prolongation are then shard-local
# reshapes); below that the coarse problem is small, so it is all_gather'd
# and solved REDUNDANTLY and EXACTLY on every shard by DCT diagonalization
# (ops/dctpoisson.py) — the standard parallel-MG answer to the coarse-grid
# bottleneck (smoothing a tiny grid through halo exchanges would need
# O(global extent) coupled iterations; the replicated direct solve needs
# none).
#
# Smoothing at distributed levels reuses the bitwise-parity half-sweep
# choreography (stencil2d/3d rb_exchange_per_sweep with halo=1 masks), so
# the distributed V-cycle applies the same per-element arithmetic as the
# single-device cycle.

# distributed levels coarsen while every LOCAL extent stays even and at
# least 2*min_size — the same rule as the single-device plan, applied to the
# shard-local extents (mg_levels is the single home of the coarsening rule)


def _record_mg_dispatch(key: str, sm: dict, n_levels: int) -> None:
    """Observability twin of the SOR solvers' dispatch records: which MG
    levels smooth through the per-shard Pallas kernel (informational —
    driver artifacts, tests)."""
    from ..utils import dispatch as _dispatch

    if sm:
        lvls = sorted({lvl for (lvl, _) in sm})
        _dispatch.record(
            key, f"pallas_sm L{','.join(map(str, lvls))}/{n_levels}"
        )
    else:
        _dispatch.record(key, "jnp_sm")


def _pallas_dist_smoother_2d(comm, gjmax, gimax, jl, il, dxl, dyl, dtype, n,
                             fluid=None, backend="auto"):
    """Distributed twin of _pallas_smoother_2d: build
    `smooth(p_ext, rhs_ext) -> p_ext` on the halo-1 extended LOCAL block —
    one depth-2n halo exchange, then n ω=1 red-black sweeps via the
    per-shard flag-masked kernel (ops/sor_obsdist.make_rb_iters_obsdist, the
    kernel of the distributed obstacle SOR solve — VERDICT r4 item 1: the
    dist MG factories smoothed in jnp with an exchange per half-sweep).
    The returned block's ±1 ghost ring is STALE (the jnp smoother contract:
    callers re-exchange before reading shard-edge neighbours). `fluid=None`
    (the PLAIN dist MG) smooths through an all-fluid flag field: every eps
    coefficient is 1, so the arithmetic is the plain stencil up to fp
    association — ulp-equivalent, not bitwise (the quarters-layout
    precedent); obstacle callers pass their level's global flags and keep
    the obstacle solver's bitwise CA discipline. Returns None whenever
    ineligible — callers keep the jnp sweeps then."""
    from ..models.poisson import _use_pallas
    from ..parallel.stencil2d import ca_clamp, ca_supported

    if n < 1 or not _use_pallas(backend, dtype):
        return None
    # exactly n sweeps or nothing: a clamped depth would change the
    # trajectory vs the single-device smoother
    if not ca_supported(jl, il) or ca_clamp(n, jl, il) != n:
        return None
    if backend != "pallas" and jl * il < _PALLAS_SMOOTH_MIN_CELLS:
        return None
    from . import sor_pallas as sp
    from .sor_obsdist import make_rb_iters_obsdist

    H = 2 * n
    try:
        rb_k, br_k, h_k = make_rb_iters_obsdist(
            gjmax, gimax, jl, il, n, dxl, dyl, 1.0, dtype,
        )
    except ValueError:
        return None
    if rb_k is None:
        return None
    # out-of-domain deep cells are dead (zero flags): they update nothing —
    # the deep_obstacle_masks convention. Obstacle callers pass the global
    # flag field (irreducible geometry, the make_dist_obstacle_solver
    # convention); the PLAIN all-fluid field is pure index structure, built
    # O(local) from global-coordinate compares instead of replicating an
    # O(global) ones array on every shard.
    flg_deep = None
    if fluid is not None:
        flg_deep = jnp.pad(jnp.asarray(fluid, dtype), [(H - 1, H - 1)] * 2)

    def local_flags(joff, ioff):
        # deep-block cell (a, b) holds global extended index
        # (a - (H-1) + joff, b - (H-1) + ioff); inside the extended domain
        # (ghost ring included) it is fluid, beyond it dead
        gj = jnp.arange(jl + 2 * H)[:, None] - (H - 1) + joff
        gi = jnp.arange(il + 2 * H)[None, :] - (H - 1) + ioff
        inside = (
            (gj >= 0) & (gj <= gjmax + 1) & (gi >= 0) & (gi <= gimax + 1)
        )
        return inside.astype(dtype)

    def smooth(p, rhs):
        from jax import lax as _lax

        from ..parallel.comm import get_offsets, halo_exchange
        from ..parallel.stencil2d import embed_deep, strip_deep

        joff = get_offsets("j", jl)
        ioff = get_offsets("i", il)
        offs = jnp.stack([joff.astype(jnp.int32), ioff.astype(jnp.int32)])
        pd = halo_exchange(embed_deep(p, H), comm, depth=H)
        rd = halo_exchange(embed_deep(rhs, H), comm, depth=H)
        if flg_deep is None:
            flg = local_flags(joff, ioff)
        else:
            flg = _lax.dynamic_slice(
                flg_deep, (joff, ioff), (jl + 2 * H, il + 2 * H)
            )
        pp, _ = rb_k(
            offs,
            sp.pad_array(pd, br_k, h_k),
            sp.pad_array(rd, br_k, h_k),
            sp.pad_array(flg, br_k, h_k),
        )
        pd = sp.unpad_array(pp, jl + 2 * H - 2, il + 2 * H - 2, h_k)
        return strip_deep(pd, H)

    return smooth


def _pallas_dist_smoother_3d(comm, gkmax, gjmax, gimax, kl, jl, il,
                             dxl, dyl, dzl, dtype, n, fluid=None,
                             backend="auto"):
    """3-D twin of _pallas_dist_smoother_2d (kernel:
    ops/sor_obsdist3d.make_rb_iters_obsdist_3d; same stale-ghost contract,
    same all-fluid plain mode)."""
    from ..models.ns3d import _use_pallas_3d
    from ..parallel.stencil2d import ca_clamp, ca_supported

    if n < 1 or not _use_pallas_3d(backend, dtype):
        return None
    if not ca_supported(kl, jl, il) or ca_clamp(n, kl, jl, il) != n:
        return None
    if backend != "pallas" and kl * jl * il < _PALLAS_SMOOTH_MIN_CELLS:
        return None
    from .sor3d_pallas import pad_array_3d, unpad_array_3d
    from .sor_obsdist3d import make_rb_iters_obsdist_3d

    H = 2 * n
    try:
        rb_k, bk_k = make_rb_iters_obsdist_3d(
            gkmax, gjmax, gimax, kl, jl, il, n, dxl, dyl, dzl, 1.0, dtype,
        )
    except ValueError:
        return None
    if rb_k is None:
        return None
    # flag-field construction: see the 2-D twin
    flg_deep = None
    if fluid is not None:
        flg_deep = jnp.pad(jnp.asarray(fluid, dtype), [(H - 1, H - 1)] * 3)

    def local_flags(koff, joff, ioff):
        gk = (jnp.arange(kl + 2 * H) - (H - 1) + koff)[:, None, None]
        gj = (jnp.arange(jl + 2 * H) - (H - 1) + joff)[None, :, None]
        gi = (jnp.arange(il + 2 * H) - (H - 1) + ioff)[None, None, :]
        inside = (
            (gk >= 0) & (gk <= gkmax + 1)
            & (gj >= 0) & (gj <= gjmax + 1)
            & (gi >= 0) & (gi <= gimax + 1)
        )
        return inside.astype(dtype)

    def smooth(p, rhs):
        from jax import lax as _lax

        from ..parallel.comm import get_offsets, halo_exchange
        from ..parallel.stencil2d import embed_deep, strip_deep

        koff = get_offsets("k", kl)
        joff = get_offsets("j", jl)
        ioff = get_offsets("i", il)
        offs = jnp.stack([
            koff.astype(jnp.int32), joff.astype(jnp.int32),
            ioff.astype(jnp.int32),
        ])
        pd = halo_exchange(embed_deep(p, H), comm, depth=H)
        rd = halo_exchange(embed_deep(rhs, H), comm, depth=H)
        if flg_deep is None:
            flg = local_flags(koff, joff, ioff)
        else:
            flg = _lax.dynamic_slice(
                flg_deep, (koff, joff, ioff),
                (kl + 2 * H, jl + 2 * H, il + 2 * H),
            )
        pp, _ = rb_k(
            offs,
            pad_array_3d(pd, bk_k, n),
            pad_array_3d(rd, bk_k, n),
            pad_array_3d(flg, bk_k, n),
        )
        pd = unpad_array_3d(
            pp, kl + 2 * H - 2, jl + 2 * H - 2, il + 2 * H - 2, n
        )
        return strip_deep(pd, H)

    return smooth


def make_dist_mg_solve_2d(comm, imax, jmax, jl, il, dx, dy, eps, itermax,
                          dtype, n_pre: int = 2, n_post: int = 2,
                          stall_rtol=MG_STALL_RTOL, backend: str = "auto",
                          split: bool = False, fused: str = "off"):
    """Distributed-MG convergence loop (shard_map kernel side): builds
    `(p_ext, rhs_ext) -> (p_ext, res, it)` on the halo-1 extended local
    block — the same contract as the distributed SOR solve; `it` counts
    V-cycles. The replicated coarse problem is solved EXACTLY by DCT
    diagonalization on every shard (ops/dctpoisson.py). Stalled residuals
    stop the loop early per `stall_rtol` — see make_mg_solve_2d. Eligible
    levels smooth through the per-shard Pallas kernel with one deep
    exchange per n sweeps (_pallas_dist_smoother_2d); returns
    `(solve, used_pallas)` so callers can relax shard_map's check_vma
    around the pallas_call (the make_dist_obstacle_solver contract)."""
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax, dtype,
                    f"mg2d_dist {imax}x{jmax}")
    from jax import lax as _lax

    from ..parallel.comm import (
        get_offsets,
        halo_exchange,
        master_print,
        persistent_exchange,
        reduction,
    )
    from ..parallel.stencil2d import (
        ca_masks,
        rb_exchange_per_sweep,
        rb_split_iter,
    )
    from .dctpoisson import poisson_dct_2d

    Pj = comm.axis_size("j")
    Pi = comm.axis_size("i")
    levels = _truncate_levels(mg_levels(jl, il), _DCT_BOTTOM_MAX_CELLS,
                              Pj * Pi)
    cfg = []
    for lvl, (jll, ill) in enumerate(levels):
        dxl, dyl = dx * (2 ** lvl), dy * (2 ** lvl)
        dx2, dy2 = dxl * dxl, dyl * dyl
        cfg.append(
            dict(
                jl=jll, il=ill,
                jmax=jll * Pj, imax=ill * Pi,
                dx=dxl, dy=dyl,
                idx2=1.0 / dx2, idy2=1.0 / dy2,
                factor=0.5 * (dx2 * dy2) / (dx2 + dy2),  # ω=1 smoother
            )
        )

    # per-shard Pallas smoothers at eligible levels (all-fluid flag field —
    # ulp-equivalent to the jnp sweeps, see _pallas_dist_smoother_2d)
    sm = {}
    for lvl, c in enumerate(cfg):
        for nn in {n_pre, n_post}:
            if nn and (lvl, nn) not in sm:
                k = _pallas_dist_smoother_2d(
                    comm, c["jmax"], c["imax"], c["jl"], c["il"],
                    c["dx"], c["dy"], dtype, nn, backend=backend,
                )
                if k is not None:
                    sm[(lvl, nn)] = k
    _record_mg_dispatch("mg_dist", sm, len(levels))

    # the fused cycle kernel is single-device (one plane per launch); the
    # distributed build's share of tpu_mg_fused is the COARSE-LEVEL
    # CONTINUATION below: when the shard floor stopped the plan while the
    # replicated bottom could still coarsen, "on" keeps the hierarchy going
    # with a replicated jnp mini-V-cycle (its own bottom is the exact DCT)
    # instead of paying the direct solve at the gathered extents
    from ..utils import dispatch as _dispatch

    _dispatch.resolve_mg_fused(
        fused, backend, dtype, "mg_dist_fused",
        why_not="fused cycle is single-device; the distributed build gets "
                "the coarse-aggregation seam below the shard floor",
    )
    agg_vcycle = None
    cb = cfg[-1]
    if fused == "on":
        g_levels = _truncate_levels(mg_levels(cb["jmax"], cb["imax"]),
                                    _DCT_BOTTOM_MAX_CELLS)
        if len(g_levels) > 1:
            agg_vcycle = make_mg_vcycle_2d(
                cb["imax"], cb["jmax"], cb["dx"], cb["dy"], dtype,
                n_pre, n_post, backend="jnp",
            )
            _dispatch.record(
                "mg_dist_agg",
                f"replicated_vcycle (levels={len(g_levels)})",
            )

    def masks_at(lvl):
        c = cfg[lvl]
        return ca_masks(c["jl"], c["il"], 1, c["jmax"], c["imax"], dtype)

    # sweep-split smoothing (`split=True`, the overlapped-schedule
    # caller): the jnp-fallback levels post each half-sweep's depth-1
    # exchange behind the rim-2 interior update (stencil2d.rb_split_iter
    # — bitwise the serial per-half-sweep smoother). Pallas-smoothed
    # levels keep their deep-exchange sweeps either way.
    sched1 = persistent_exchange(comm, 1, dtype) if split else None
    part = tuple(d > 1 for d in comm.dims)

    def smooth(p, rhs, lvl, n):
        c = cfg[lvl]
        k = sm.get((lvl, n))
        if k is not None:
            return k(p, rhs)
        m = masks_at(lvl)
        if split:
            from ..parallel.overlap import interior_mask

            im = interior_mask((c["jl"], c["il"]), 2, partitioned=part)
            for _ in range(n):
                p, _ = rb_split_iter(p, rhs, m, sched1, im, c["factor"],
                                     c["idx2"], c["idy2"])
            return p
        for _ in range(n):
            p, _ = rb_exchange_per_sweep(
                p, rhs, m, comm, c["factor"], c["idx2"], c["idy2"]
            )
        return p

    def vcycle(p, rhs, lvl=0):
        c = cfg[lvl]
        p = smooth(p, rhs, lvl, n_pre)
        p = halo_exchange(p, comm)  # residual reads shard-edge neighbours
        r = _residual2(p, rhs, c["idx2"], c["idy2"])
        if lvl == len(levels) - 1:
            # replicated bottom solve: gather this level's residual and
            # solve it EXACTLY (DCT) on every shard, then slice own block.
            # The named scope is the declared aggregation boundary the
            # comm census keys on (analysis/commcheck).
            with jax.named_scope("mg_aggregate.gather2d"):
                rg = _lax.all_gather(r, "j", axis=0, tiled=True)
                rg = _lax.all_gather(rg, "i", axis=1, tiled=True)
            if agg_vcycle is not None:
                # coarse-level continuation: keep coarsening globally via
                # the replicated mini-V-cycle on the gathered residual
                eg = agg_vcycle(_embed2(jnp.zeros_like(rg)), _embed2(rg))
                e = eg[1:-1, 1:-1]
            else:
                e = poisson_dct_2d(rg, c["dx"], c["dy"])
            joff = get_offsets("j", c["jl"])
            ioff = get_offsets("i", c["il"])
            e_own = _lax.dynamic_slice(e, (joff, ioff), (c["jl"], c["il"]))
            p = p.at[1:-1, 1:-1].add(e_own)
        else:
            r2 = _restrict2(r)
            e2 = vcycle(_embed2(jnp.zeros_like(r2)), _embed2(r2), lvl + 1)
            p = p.at[1:-1, 1:-1].add(_prolong2(e2[1:-1, 1:-1]))
        from ..parallel.stencil2d import neumann_masked

        p = neumann_masked(p, masks_at(lvl))
        return smooth(p, rhs, lvl, n_post)

    idx2, idy2 = 1.0 / (dx * dx), 1.0 / (dy * dy)
    norm = float(imax * jmax)
    epssq = eps * eps

    def solve(p, rhs):
        def cond(c):
            _, res, prev, it = c
            # _stalled: identical stopping contract to the single-device loop
            return jnp.logical_and(
                jnp.logical_and(res >= epssq, it < itermax),
                jnp.logical_not(_stalled(prev, res, it, stall_rtol)),
            )

        def body(c):
            p, prev, _, it = c
            p = vcycle(p, rhs)
            p = halo_exchange(p, comm)
            r = _residual2(p, rhs, idx2, idy2)
            res = reduction(jnp.sum(r * r), comm, "sum") / norm
            if _flags.debug():
                # ≙ -DDEBUG Residuum per V-cycle, rank-0 shard only (the
                # -single-device _mg_converge_loop's print, distributed)
                master_print(comm, "{} Residuum: {}", it, res)
            return p, res, prev, it + 1

        p, res, _, it = lax.while_loop(
            cond, body,
            (p, jnp.asarray(1.0, dtype), jnp.asarray(jnp.inf, dtype),
             jnp.asarray(0, jnp.int32)),
        )
        # the body returns p freshly exchanged; this trailing exchange only
        # matters on the zero-trip path (eps >= 1 skips the loop) and costs
        # one ppermute round per SOLVE, not per cycle
        return halo_exchange(p, comm), res, it

    return solve, bool(sm)


def make_dist_mg_solve_3d(comm, imax, jmax, kmax, kl, jl, il, dx, dy, dz,
                          eps, itermax, dtype, n_pre: int = 2,
                          n_post: int = 2, stall_rtol=MG_STALL_RTOL,
                          backend: str = "auto", split: bool = False,
                          fused: str = "off"):
    """3-D twin of make_dist_mg_solve_2d (same stall_rtol contract; returns
    `(solve, used_pallas)` like the 2-D twin; `split` swaps the jnp-
    fallback smoother levels to the sweep-split form)."""
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax * kmax, dtype,
                    f"mg3d_dist {imax}x{jmax}x{kmax}")
    from jax import lax as _lax

    from ..parallel.comm import (
        get_offsets,
        halo_exchange,
        master_print,
        persistent_exchange,
        reduction,
    )
    from ..parallel.stencil3d import (
        ca_masks_3d,
        neumann_masked_3d,
        rb_exchange_per_sweep_3d,
        rb_split_iter_3d,
    )

    from .dctpoisson import poisson_dct_3d

    Pk = comm.axis_size("k")
    Pj = comm.axis_size("j")
    Pi = comm.axis_size("i")
    levels = _truncate_levels(mg_levels(kl, jl, il), _DCT_BOTTOM_MAX_CELLS,
                              Pk * Pj * Pi)
    cfg = []
    for lvl, (kll, jll, ill) in enumerate(levels):
        dxl, dyl, dzl = dx * (2 ** lvl), dy * (2 ** lvl), dz * (2 ** lvl)
        dx2, dy2, dz2 = dxl * dxl, dyl * dyl, dzl * dzl
        cfg.append(
            dict(
                kl=kll, jl=jll, il=ill,
                kmax=kll * Pk, jmax=jll * Pj, imax=ill * Pi,
                dx=dxl, dy=dyl, dz=dzl,
                idx2=1.0 / dx2, idy2=1.0 / dy2, idz2=1.0 / dz2,
                factor=0.5 * (dx2 * dy2 * dz2)
                / (dy2 * dz2 + dx2 * dz2 + dx2 * dy2),
            )
        )

    # per-shard Pallas smoothers at eligible levels (see the 2-D twin)
    sm = {}
    for lvl, c in enumerate(cfg):
        for nn in {n_pre, n_post}:
            if nn and (lvl, nn) not in sm:
                k = _pallas_dist_smoother_3d(
                    comm, c["kmax"], c["jmax"], c["imax"],
                    c["kl"], c["jl"], c["il"],
                    c["dx"], c["dy"], c["dz"], dtype, nn, backend=backend,
                )
                if k is not None:
                    sm[(lvl, nn)] = k
    _record_mg_dispatch("mg_dist_3d", sm, len(levels))

    # coarse-level continuation below the shard floor — see the 2-D twin
    from ..utils import dispatch as _dispatch

    _dispatch.resolve_mg_fused(
        fused, backend, dtype, "mg_dist_fused",
        why_not="fused cycle is single-device; the distributed build gets "
                "the coarse-aggregation seam below the shard floor",
    )
    agg_vcycle = None
    cb = cfg[-1]
    if fused == "on":
        g_levels = _truncate_levels(
            mg_levels(cb["kmax"], cb["jmax"], cb["imax"]),
            _DCT_BOTTOM_MAX_CELLS,
        )
        if len(g_levels) > 1:
            agg_vcycle = make_mg_vcycle_3d(
                cb["imax"], cb["jmax"], cb["kmax"],
                cb["dx"], cb["dy"], cb["dz"], dtype,
                n_pre, n_post, backend="jnp",
            )
            _dispatch.record(
                "mg_dist_agg_3d",
                f"replicated_vcycle (levels={len(g_levels)})",
            )

    def masks_at(lvl):
        c = cfg[lvl]
        return ca_masks_3d(c["kl"], c["jl"], c["il"], 1,
                           c["kmax"], c["jmax"], c["imax"], dtype)

    # sweep-split smoothing (see the 2-D twin)
    sched1 = persistent_exchange(comm, 1, dtype) if split else None
    part = tuple(d > 1 for d in comm.dims)

    def smooth(p, rhs, lvl, n):
        c = cfg[lvl]
        k = sm.get((lvl, n))
        if k is not None:
            return k(p, rhs)
        m = masks_at(lvl)
        if split:
            from ..parallel.overlap import interior_mask

            im = interior_mask((c["kl"], c["jl"], c["il"]), 2,
                               partitioned=part)
            for _ in range(n):
                p, _ = rb_split_iter_3d(
                    p, rhs, m, sched1, im, c["factor"],
                    c["idx2"], c["idy2"], c["idz2"])
            return p
        for _ in range(n):
            p, _ = rb_exchange_per_sweep_3d(
                p, rhs, m, comm, c["factor"],
                c["idx2"], c["idy2"], c["idz2"],
            )
        return p

    def vcycle(p, rhs, lvl=0):
        c = cfg[lvl]
        p = smooth(p, rhs, lvl, n_pre)
        p = halo_exchange(p, comm)
        r = _residual3(p, rhs, c["idx2"], c["idy2"], c["idz2"])
        if lvl == len(levels) - 1:
            # declared aggregation boundary — see the 2-D twin
            with jax.named_scope("mg_aggregate.gather3d"):
                rg = _lax.all_gather(r, "k", axis=0, tiled=True)
                rg = _lax.all_gather(rg, "j", axis=1, tiled=True)
                rg = _lax.all_gather(rg, "i", axis=2, tiled=True)
            if agg_vcycle is not None:
                eg = agg_vcycle(_embed3(jnp.zeros_like(rg)), _embed3(rg))
                e = eg[1:-1, 1:-1, 1:-1]
            else:
                e = poisson_dct_3d(rg, c["dx"], c["dy"], c["dz"])
            koff = get_offsets("k", c["kl"])
            joff = get_offsets("j", c["jl"])
            ioff = get_offsets("i", c["il"])
            e_own = _lax.dynamic_slice(
                e, (koff, joff, ioff), (c["kl"], c["jl"], c["il"])
            )
            p = p.at[1:-1, 1:-1, 1:-1].add(e_own)
        else:
            r2 = _restrict3(r)
            e2 = vcycle(_embed3(jnp.zeros_like(r2)), _embed3(r2), lvl + 1)
            p = p.at[1:-1, 1:-1, 1:-1].add(_prolong3(e2[1:-1, 1:-1, 1:-1]))
        p = neumann_masked_3d(p, masks_at(lvl))
        return smooth(p, rhs, lvl, n_post)

    idx2 = 1.0 / (dx * dx)
    idy2 = 1.0 / (dy * dy)
    idz2 = 1.0 / (dz * dz)
    norm = float(imax * jmax * kmax)
    epssq = eps * eps

    def solve(p, rhs):
        def cond(c):
            _, res, prev, it = c
            return jnp.logical_and(
                jnp.logical_and(res >= epssq, it < itermax),
                jnp.logical_not(_stalled(prev, res, it, stall_rtol)),
            )

        def body(c):
            p, prev, _, it = c
            p = vcycle(p, rhs)
            p = halo_exchange(p, comm)
            r = _residual3(p, rhs, idx2, idy2, idz2)
            res = reduction(jnp.sum(r * r), comm, "sum") / norm
            if _flags.debug():
                # ≙ -DDEBUG Residuum per V-cycle, rank-0 shard only (the
                # -single-device _mg_converge_loop's print, distributed)
                master_print(comm, "{} Residuum: {}", it, res)
            return p, res, prev, it + 1

        p, res, _, it = lax.while_loop(
            cond, body,
            (p, jnp.asarray(1.0, dtype), jnp.asarray(jnp.inf, dtype),
             jnp.asarray(0, jnp.int32)),
        )
        # zero-trip safety; see the 2-D twin
        return halo_exchange(p, comm), res, it

    return solve, bool(sm)


def make_dist_obstacle_mg_solve_2d(comm, imax, jmax, jl, il, dx, dy, eps,
                                   itermax, masks, dtype, n_pre: int = 2,
                                   n_post: int = 2, n_coarse: int = 60,
                                   stall_rtol=MG_STALL_RTOL,
                                   backend: str = "auto",
                                   fused: str = "off"):
    """Distributed obstacle-capable MG (shard_map kernel side): the
    composition VERDICT r3 item 6 asked for — the dist-MG skeleton
    (make_dist_mg_solve_2d) with the obstacle coarsening/rediscretization of
    make_obstacle_mg_solve_2d. Builds `(p_ext, rhs_ext) -> (p_ext, res, it)`
    on the halo-1 extended local block; `it` counts V-cycles; residual
    normalized by the GLOBAL fluid-cell count (the distributed obstacle
    solve contract, ops/obstacle.make_dist_obstacle_solver).

    Geometry: the GLOBAL flag field coarsens by fluid-ANY per level
    (coarsen_fluid) and every level rediscretizes the eps-coefficient
    operator from its own global flags at ω=1 (ops/obstacle.make_masks);
    each shard slices its block inside the trace (shard_masks), so the
    distributed smoothing applies the exact single-device sor_pass_obstacle
    arithmetic between halo exchanges. Eligible levels smooth through the
    per-shard flag-masked Pallas kernel with ONE deep exchange per n sweeps
    (_pallas_dist_smoother_2d — same CA discipline as the distributed
    obstacle SOR, bitwise-equal to the jnp sweeps); the rest keep the
    exchange-per-half-sweep jnp passes. Returns `(solve, used_pallas)` —
    the make_dist_obstacle_solver contract (callers relax shard_map's
    check_vma around the pallas_call).

    Bottom level: obstacles rule out the DCT direct solve, so the bottom
    problem is all_gather'd and solved REDUNDANTLY on every shard — exactly
    via the dense pinv of the global bottom operator
    (_dense_obstacle_bottom, one small matmul; n_coarse ω=1 sweeps only as
    the fallback when the global bottom exceeds the pinv budget) — then
    each shard slices its own block back out. Stalled residuals stop the
    loop early per `stall_rtol` — see make_mg_solve_2d."""
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax, dtype,
                    f"mg2d_dist_obstacle {imax}x{jmax}")
    import numpy as np

    from jax import lax as _lax

    from ..parallel.comm import (
        get_offsets,
        halo_exchange,
        master_print,
        reduction,
    )
    from ..parallel.stencil2d import ca_masks, neumann_masked
    from .obstacle import (
        make_masks,
        obstacle_residual,
        shard_masks,
        sor_pass_obstacle,
    )
    from .sor import checkerboard_mask

    Pj = comm.axis_size("j")
    Pi = comm.axis_size("i")
    # stop coarsening once the GLOBAL bottom fits the dense-pinv budget
    # (same reasoning as the single-device plan truncation)
    levels = _truncate_levels(mg_levels(jl, il), _DENSE_BOTTOM_MAX_CELLS,
                              Pj * Pi)
    fine_fluid = np.asarray(masks.fluid).astype(bool)
    cfg = []
    fluid = fine_fluid
    for lvl, (jll, ill) in enumerate(levels):
        dxl, dyl = dx * (2 ** lvl), dy * (2 ** lvl)
        if lvl > 0:
            fluid = coarsen_fluid(fluid)
        gj, gi = jll * Pj, ill * Pi
        cfg.append(
            dict(
                jl=jll, il=ill, jmax=gj, imax=gi,
                idx2=1.0 / (dxl * dxl), idy2=1.0 / (dyl * dyl),
                # GLOBAL ω=1 masks; shards slice inside the trace
                m=make_masks(fluid, dxl, dyl, 1.0, dtype),
            )
        )
    # replicated bottom machinery — ONLY the bottom level works globally
    cb = cfg[-1]
    lvl_b = len(levels) - 1
    if cb["jmax"] * cb["imax"] <= _DENSE_BOTTOM_MAX_CELLS:
        bottom_exact = _dense_obstacle_bottom(
            cb["m"].fluid, dx * 2 ** lvl_b, dy * 2 ** lvl_b, dtype,
        )
    else:
        bottom_exact = None  # smoothed fallback needs the checkerboards
        cb["red_g"] = checkerboard_mask(cb["jmax"], cb["imax"], 0, dtype)
        cb["black_g"] = checkerboard_mask(cb["jmax"], cb["imax"], 1, dtype)
    # the fused cycle is single-device; the distributed obstacle build's
    # share of tpu_mg_fused is the FFT-preconditioned coarse application:
    # "on" replaces the n_coarse smooth-to-death unroll at over-budget
    # replicated bottoms (shard floor stalled the plan above the pinv
    # budget) with _FFT_COARSE_ITERS Richardson+DCT rounds
    from ..utils import dispatch as _dispatch

    _dispatch.resolve_mg_fused(
        fused, backend, dtype, "mg_dist_fused",
        why_not="fused cycle is single-device; the distributed build gets "
                "the coarse-aggregation seam below the shard floor",
    )
    bottom_fft = None
    if bottom_exact is None and fused == "on":
        bottom_fft = _make_fft_coarse_2d(
            cb["m"], dx * 2 ** lvl_b, dy * 2 ** lvl_b,
            cb["idx2"], cb["idy2"], cb["red_g"], cb["black_g"],
        )
        _dispatch.record("mg_dist_obstacle_coarse",
                         f"fft_richardson (n={_FFT_COARSE_ITERS})")

    # per-shard Pallas smoothers at eligible levels: the level's GLOBAL
    # flag field keeps the CA discipline bitwise (the obstacle-SOR kernel
    # at ω=1 — VERDICT r4 item 1); the bottom never smooths distributed
    sm = {}
    for lvl in range(len(levels) - 1):
        c = cfg[lvl]
        for nn in {n_pre, n_post}:
            if nn and (lvl, nn) not in sm:
                k = _pallas_dist_smoother_2d(
                    comm, c["jmax"], c["imax"], c["jl"], c["il"],
                    dx * 2 ** lvl, dy * 2 ** lvl, dtype, nn,
                    fluid=c["m"].fluid, backend=backend,
                )
                if k is not None:
                    sm[(lvl, nn)] = k
    _record_mg_dispatch("obstacle_dist_mg", sm, len(levels))

    def smooth(p, rhs, lvl, n):
        c = cfg[lvl]
        k = sm.get((lvl, n))
        if k is not None:
            return k(p, rhs)
        cm = ca_masks(c["jl"], c["il"], 1, c["jmax"], c["imax"], dtype)
        ml = shard_masks(c["m"], c["jl"], c["il"])
        red = cm["red"][1:-1, 1:-1]
        black = cm["black"][1:-1, 1:-1]
        for _ in range(n):
            p = halo_exchange(p, comm)
            p, _ = sor_pass_obstacle(p, rhs, red, ml, c["idx2"], c["idy2"])
            p = halo_exchange(p, comm)
            p, _ = sor_pass_obstacle(p, rhs, black, ml, c["idx2"], c["idy2"])
            p = neumann_masked(p, cm)
        return p

    def bottom(p, rhs, lvl):
        # replicated bottom: gather interiors, solve the global problem on
        # every shard (identical constants -> identical results), slice own
        c = cfg[lvl]
        # declared aggregation boundary (analysis/commcheck census)
        with jax.named_scope("mg_aggregate.obstacle2d"):
            pg = _lax.all_gather(p[1:-1, 1:-1], "j", axis=0, tiled=True)
            pg = _lax.all_gather(pg, "i", axis=1, tiled=True)
            rg = _lax.all_gather(rhs[1:-1, 1:-1], "j", axis=0, tiled=True)
            rg = _lax.all_gather(rg, "i", axis=1, tiled=True)
        pe = _neumann2(_embed2(pg))
        re = _embed2(rg)
        if bottom_exact is not None:
            pe = bottom_exact(pe, re)
        elif bottom_fft is not None:
            pe = bottom_fft(pe, re)
        else:
            for _ in range(n_coarse):
                pe, _ = sor_pass_obstacle(
                    pe, re, c["red_g"], c["m"], c["idx2"], c["idy2"]
                )
                pe, _ = sor_pass_obstacle(
                    pe, re, c["black_g"], c["m"], c["idx2"], c["idy2"]
                )
                pe = _neumann2(pe)
        joff = get_offsets("j", c["jl"])
        ioff = get_offsets("i", c["il"])
        return _lax.dynamic_slice(
            pe, (joff, ioff), (c["jl"] + 2, c["il"] + 2)
        )

    def vcycle(p, rhs, lvl=0):
        c = cfg[lvl]
        if lvl == len(levels) - 1:
            return bottom(p, rhs, lvl)
        p = smooth(p, rhs, lvl, n_pre)
        p = halo_exchange(p, comm)  # residual reads shard-edge neighbours
        ml = shard_masks(c["m"], c["jl"], c["il"])
        r = obstacle_residual(p, rhs, ml, c["idx2"], c["idy2"])
        r2 = _restrict2(r)
        e2 = vcycle(_embed2(jnp.zeros_like(r2)), _embed2(r2), lvl + 1)
        # inject into fluid cells only (obstacle cells stay untouched)
        p = p.at[1:-1, 1:-1].add(_prolong2(e2[1:-1, 1:-1]) * ml.p_mask)
        cm = ca_masks(c["jl"], c["il"], 1, c["jmax"], c["imax"], dtype)
        p = neumann_masked(p, cm)
        return smooth(p, rhs, lvl, n_post)

    fine = cfg[0]
    norm = fine["m"].n_fluid
    epssq = eps * eps

    def solve(p, rhs):
        ml = shard_masks(fine["m"], fine["jl"], fine["il"])

        def cond(c):
            _, res, prev, it = c
            return jnp.logical_and(
                jnp.logical_and(res >= epssq, it < itermax),
                jnp.logical_not(_stalled(prev, res, it, stall_rtol)),
            )

        def body(c):
            p, prev, _, it = c
            p = vcycle(p, rhs)
            p = halo_exchange(p, comm)
            r = obstacle_residual(p, rhs, ml, fine["idx2"], fine["idy2"])
            res = reduction(jnp.sum(r * r), comm, "sum") / norm
            if _flags.debug():
                # ≙ -DDEBUG Residuum per V-cycle, rank-0 shard only (the
                # -single-device _mg_converge_loop's print, distributed)
                master_print(comm, "{} Residuum: {}", it, res)
            return p, res, prev, it + 1

        p, res, _, it = lax.while_loop(
            cond, body,
            (p, jnp.asarray(1.0, dtype), jnp.asarray(jnp.inf, dtype),
             jnp.asarray(0, jnp.int32)),
        )
        # zero-trip safety; see make_dist_mg_solve_2d
        return halo_exchange(p, comm), res, it

    return solve, bool(sm)


# ----------------------------------------------------------------------
# Obstacle multigrid (3-D): the same design as the 2-D obstacle MG —
# fluid-ANY flag coarsening, per-level rediscretized eps-coefficient
# operators at ω=1, dense exact bottom — with the 3-D stencil machinery
# (ops/obstacle3d.py) and 2×2×2 transfer operators.
# ----------------------------------------------------------------------


def coarsen_fluid_3d(fluid: "np.ndarray") -> "np.ndarray":
    """(K+2, J+2, I+2) bool flags -> coarse: interior cell fluid iff ANY of
    its 2x2x2 fine cells is (the conservative choice, as in 2-D); the ghost
    shell stays fluid."""
    import numpy as np

    fi = fluid[1:-1, 1:-1, 1:-1]
    K, J, I = fi.shape
    ci = fi.reshape(K // 2, 2, J // 2, 2, I // 2, 2).any(axis=(1, 3, 5))
    out = np.ones((K // 2 + 2, J // 2 + 2, I // 2 + 2), dtype=bool)
    out[1:-1, 1:-1, 1:-1] = ci
    return out


def _dense_obstacle_bottom_3d(fluid, dxl, dyl, dzl, dtype):
    """3-D twin of _dense_obstacle_bottom: trace-time pinv of the 6-point
    eps-coefficient all-Neumann operator on the (small) bottom grid."""
    import numpy as np

    fl = np.asarray(fluid)[1:-1, 1:-1, 1:-1].astype(bool)
    K, J, I = fl.shape
    N = K * J * I
    idx2 = 1.0 / (dxl * dxl)
    idy2 = 1.0 / (dyl * dyl)
    idz2 = 1.0 / (dzl * dzl)
    A = np.zeros((N, N))

    def idx(k, j, i):
        return (k * J + j) * I + i

    for k in range(K):
        for j in range(J):
            for i in range(I):
                kk = idx(k, j, i)
                if not fl[k, j, i]:
                    A[kk, kk] = 1.0
                    continue
                for dk, dj, di, w in (
                    (0, 0, 1, idx2), (0, 0, -1, idx2),
                    (0, 1, 0, idy2), (0, -1, 0, idy2),
                    (1, 0, 0, idz2), (-1, 0, 0, idz2),
                ):
                    k2, j2, i2 = k + dk, j + dj, i + di
                    if not (0 <= k2 < K and 0 <= j2 < J and 0 <= i2 < I):
                        continue  # wall ghost: Neumann cancels the term
                    if not fl[k2, j2, i2]:
                        continue  # obstacle neighbour: eps is 0
                    A[kk, idx(k2, j2, i2)] += w
                    A[kk, kk] -= w
    Apinv = jnp.asarray(np.linalg.pinv(A), dtype)
    fl_mask = jnp.asarray(fl.reshape(-1), dtype)

    def solve_exact(p, rhs):
        e = (Apinv @ (rhs[1:-1, 1:-1, 1:-1].reshape(-1) * fl_mask))
        e = e.reshape(K, J, I)
        from ..models.ns3d import neumann_faces_3d

        return neumann_faces_3d(
            jnp.zeros_like(p).at[1:-1, 1:-1, 1:-1].set(e)
        )

    return solve_exact


def make_obstacle_mg_solve_3d(imax, jmax, kmax, dx, dy, dz, eps, itermax,
                              masks, dtype, n_pre: int = 2, n_post: int = 2,
                              n_coarse: int = 60,
                              stall_rtol=MG_STALL_RTOL,
                              backend: str = "auto", fused: str = "off"):
    """3-D obstacle-capable MG convergence loop
    `(p_ext, rhs_ext) -> (p_ext, res, it)` — the 3-D twin of
    make_obstacle_mg_solve_2d: fluid-ANY coarsening (coarsen_fluid_3d),
    every level rediscretized at ω=1 from its own flags
    (ops/obstacle3d.make_masks_3d), residual normalized by the FLUID cell
    count, exact dense bottom (_dense_obstacle_bottom_3d; `n_coarse`
    smoothing only as the over-budget fallback). `it` counts V-cycles;
    stalls stop the loop early per `stall_rtol` — see make_mg_solve_2d."""
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax * kmax, dtype,
                    f"mg3d_obstacle {imax}x{jmax}x{kmax}")
    import numpy as np

    from ..models.ns3d import checkerboard_mask_3d, neumann_faces_3d
    from .obstacle3d import (
        make_masks_3d,
        obstacle_residual_3d,
        sor_pass_obstacle_3d,
    )

    levels = _truncate_levels(mg_levels(kmax, jmax, imax),
                              _DENSE_BOTTOM_MAX_CELLS)
    use_fused = _resolve_fused_solo(levels, dtype, fused, backend,
                                    "mg3d_obstacle_fused")
    fine_fluid = np.asarray(masks.fluid).astype(bool)
    cfg = []
    fluid = fine_fluid
    for lvl, (kl, jl, il) in enumerate(levels):
        dxl, dyl, dzl = dx * 2 ** lvl, dy * 2 ** lvl, dz * 2 ** lvl
        if lvl > 0:
            fluid = coarsen_fluid_3d(fluid)
        cfg.append(
            dict(
                m=make_masks_3d(fluid, dxl, dyl, dzl, 1.0, dtype),
                idx2=1.0 / (dxl * dxl),
                idy2=1.0 / (dyl * dyl),
                idz2=1.0 / (dzl * dzl),
                # odd-then-even: the sweep order of the 3-D obstacle SOR
                # solver (make_obstacle_solver_fn_3d)
                odd=checkerboard_mask_3d(kl, jl, il, 1, dtype),
                even=checkerboard_mask_3d(kl, jl, il, 0, dtype),
                # fused cycles smooth in-kernel: skip the ladder smoothers
                sm={} if use_fused else {
                    n: _pallas_smoother_3d(il, jl, kl, dxl, dyl, dzl,
                                           dtype, n, fluid=fluid,
                                           backend=backend)
                    for n in {n_pre, n_post} if n
                },
            )
        )

    kl_b, jl_b, il_b = levels[-1]
    lvl_b = len(levels) - 1
    bottom_exact = (
        _dense_obstacle_bottom_3d(
            cfg[-1]["m"].fluid, dx * 2 ** lvl_b, dy * 2 ** lvl_b,
            dz * 2 ** lvl_b, dtype,
        )
        if kl_b * jl_b * il_b <= _DENSE_BOTTOM_MAX_CELLS
        else None
    )
    bottom_fft = None
    if bottom_exact is None and fused == "on":
        # over-budget bottom + tpu_mg_fused on: FFT-preconditioned
        # Richardson rounds replace the n_coarse unroll — see the 2-D twin
        from ..utils import dispatch as _dispatch

        cb = cfg[-1]
        bottom_fft = _make_fft_coarse_3d(
            cb["m"], dx * 2 ** lvl_b, dy * 2 ** lvl_b, dz * 2 ** lvl_b,
            cb["idx2"], cb["idy2"], cb["idz2"], cb["odd"], cb["even"],
        )
        _dispatch.record("mg3d_obstacle_coarse",
                         f"fft_richardson (n={_FFT_COARSE_ITERS})")

    def smooth(p, rhs, lvl, n):
        c = cfg[lvl]
        k = c["sm"].get(n)
        if k is not None:
            return k(p, rhs)
        for _ in range(n):
            p, _ = sor_pass_obstacle_3d(
                p, rhs, c["odd"], c["m"], c["idx2"], c["idy2"], c["idz2"]
            )
            p, _ = sor_pass_obstacle_3d(
                p, rhs, c["even"], c["m"], c["idx2"], c["idy2"], c["idz2"]
            )
            p = neumann_faces_3d(p)
        return p

    def bottom(p, rhs):
        if bottom_exact is not None:
            return bottom_exact(p, rhs)
        if bottom_fft is not None:
            return bottom_fft(p, rhs)
        return smooth(p, rhs, len(cfg) - 1, n_coarse)

    def vcycle(p, rhs, lvl=0):
        c = cfg[lvl]
        if lvl == len(cfg) - 1:
            return bottom(p, rhs)
        p = smooth(p, rhs, lvl, n_pre)
        r = obstacle_residual_3d(
            p, rhs, c["m"], c["idx2"], c["idy2"], c["idz2"]
        )
        r2 = _restrict3(r)
        e2 = vcycle(_embed3(jnp.zeros_like(r2)), _embed3(r2), lvl + 1)
        # inject into fluid cells only
        p = p.at[1:-1, 1:-1, 1:-1].add(
            _prolong3(e2[1:-1, 1:-1, 1:-1]) * c["m"].p_mask
        )
        p = neumann_faces_3d(p)
        return smooth(p, rhs, lvl, n_post)

    cycle = vcycle
    if use_fused:
        from . import mg_fused as mf

        down, up, plane = mf.make_cycle_kernels(
            levels, (dx, dy, dz), dtype, n_pre, n_post,
            fluid_levels=[np.asarray(c["m"].fluid) for c in cfg],
            factor_levels=[c["m"].factor for c in cfg],
        )
        kb_f, jb_f, ib_f = levels[-1]

        def vcycle_fused(p, rhs):
            # two launches + the exact jnp bottom — see the 2-D twin
            pstk, rstk = down(mf.pad_plane(p, plane),
                              mf.pad_plane(rhs, plane))
            rb = rstk[-1][: kb_f + 2, : jb_f + 2, : ib_f + 2]
            pbot = bottom(jnp.zeros_like(rb), rb)
            return mf.unpad_plane(
                up(pstk, rstk, mf.pad_plane(pbot, plane)),
                (kmax, jmax, imax),
            )

        cycle = vcycle_fused

    fine = cfg[0]
    return _mg_converge_loop(
        cycle,
        lambda p, rhs: obstacle_residual_3d(
            p, rhs, fine["m"], fine["idx2"], fine["idy2"], fine["idz2"]
        ),
        float(fine["m"].n_fluid), eps, itermax, dtype, stall_rtol,
    )


def make_dist_obstacle_mg_solve_3d(comm, imax, jmax, kmax, kl, jl, il,
                                   dx, dy, dz, eps, itermax, masks, dtype,
                                   n_pre: int = 2, n_post: int = 2,
                                   n_coarse: int = 60,
                                   stall_rtol=MG_STALL_RTOL,
                                   backend: str = "auto",
                                   fused: str = "off"):
    """Distributed 3-D obstacle-capable MG (shard_map kernel side) — the
    3-D twin of make_dist_obstacle_mg_solve_2d: GLOBAL flags coarsen by
    fluid-ANY per level, every level rediscretizes at ω=1 from its own
    global flags (shards slice inside the trace, shard_masks_3d); eligible
    levels smooth through the per-shard flag-masked 3-D Pallas kernel with
    one deep exchange per n sweeps (_pallas_dist_smoother_3d), the rest
    exchange-per-half-sweep with the exact single-device
    sor_pass_obstacle_3d arithmetic. The bottom problem is all_gather'd
    and solved exactly on every shard by the dense 3-D pinv
    (_dense_obstacle_bottom_3d; `n_coarse` global sweeps only as the
    over-budget fallback). Residual normalized by the GLOBAL fluid count;
    `it` counts V-cycles; stalls stop the loop early per `stall_rtol`.
    Returns `(solve, used_pallas)` — the make_dist_obstacle_solver
    contract."""
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax * kmax, dtype,
                    f"mg3d_dist_obstacle {imax}x{jmax}x{kmax}")
    import numpy as np

    from jax import lax as _lax

    from ..models.ns3d import checkerboard_mask_3d, neumann_faces_3d
    from ..parallel.comm import (
        get_offsets,
        halo_exchange,
        master_print,
        reduction,
    )
    from ..parallel.stencil3d import ca_masks_3d, neumann_masked_3d
    from .obstacle3d import (
        make_masks_3d,
        obstacle_residual_3d,
        shard_masks_3d,
        sor_pass_obstacle_3d,
    )

    Pk = comm.axis_size("k")
    Pj = comm.axis_size("j")
    Pi = comm.axis_size("i")
    levels = _truncate_levels(mg_levels(kl, jl, il), _DENSE_BOTTOM_MAX_CELLS,
                              Pk * Pj * Pi)
    fine_fluid = np.asarray(masks.fluid).astype(bool)
    cfg = []
    fluid = fine_fluid
    for lvl, (kll, jll, ill) in enumerate(levels):
        dxl, dyl, dzl = dx * 2 ** lvl, dy * 2 ** lvl, dz * 2 ** lvl
        if lvl > 0:
            fluid = coarsen_fluid_3d(fluid)
        cfg.append(
            dict(
                kl=kll, jl=jll, il=ill,
                kmax=kll * Pk, jmax=jll * Pj, imax=ill * Pi,
                idx2=1.0 / (dxl * dxl),
                idy2=1.0 / (dyl * dyl),
                idz2=1.0 / (dzl * dzl),
                m=make_masks_3d(fluid, dxl, dyl, dzl, 1.0, dtype),  # GLOBAL
            )
        )
    cb = cfg[-1]
    lvl_b = len(levels) - 1
    if cb["kmax"] * cb["jmax"] * cb["imax"] <= _DENSE_BOTTOM_MAX_CELLS:
        bottom_exact = _dense_obstacle_bottom_3d(
            cb["m"].fluid, dx * 2 ** lvl_b, dy * 2 ** lvl_b,
            dz * 2 ** lvl_b, dtype,
        )
    else:
        bottom_exact = None  # smoothed fallback needs global checkerboards
        cb["odd_g"] = checkerboard_mask_3d(
            cb["kmax"], cb["jmax"], cb["imax"], 1, dtype)
        cb["even_g"] = checkerboard_mask_3d(
            cb["kmax"], cb["jmax"], cb["imax"], 0, dtype)
    # tpu_mg_fused share of the distributed obstacle build — see the 2-D
    # twin (FFT-preconditioned coarse application at over-budget bottoms)
    from ..utils import dispatch as _dispatch

    _dispatch.resolve_mg_fused(
        fused, backend, dtype, "mg_dist_fused",
        why_not="fused cycle is single-device; the distributed build gets "
                "the coarse-aggregation seam below the shard floor",
    )
    bottom_fft = None
    if bottom_exact is None and fused == "on":
        bottom_fft = _make_fft_coarse_3d(
            cb["m"], dx * 2 ** lvl_b, dy * 2 ** lvl_b, dz * 2 ** lvl_b,
            cb["idx2"], cb["idy2"], cb["idz2"],
            cb["odd_g"], cb["even_g"],
        )
        _dispatch.record("mg_dist_obstacle_coarse_3d",
                         f"fft_richardson (n={_FFT_COARSE_ITERS})")

    # per-shard Pallas smoothers at eligible levels (the level's GLOBAL
    # flag field keeps the CA discipline bitwise — see the 2-D twin)
    sm = {}
    for lvl in range(len(levels) - 1):
        c = cfg[lvl]
        for nn in {n_pre, n_post}:
            if nn and (lvl, nn) not in sm:
                k = _pallas_dist_smoother_3d(
                    comm, c["kmax"], c["jmax"], c["imax"],
                    c["kl"], c["jl"], c["il"],
                    dx * 2 ** lvl, dy * 2 ** lvl, dz * 2 ** lvl,
                    dtype, nn, fluid=c["m"].fluid, backend=backend,
                )
                if k is not None:
                    sm[(lvl, nn)] = k
    _record_mg_dispatch("obstacle_dist_mg_3d", sm, len(levels))

    def smooth(p, rhs, lvl, n):
        c = cfg[lvl]
        k = sm.get((lvl, n))
        if k is not None:
            return k(p, rhs)
        cm = ca_masks_3d(c["kl"], c["jl"], c["il"], 1,
                         c["kmax"], c["jmax"], c["imax"], dtype)
        ml = shard_masks_3d(c["m"], c["kl"], c["jl"], c["il"])
        # odd-then-even: the single-device 3-D obstacle sweep order
        odd = cm["odd"][1:-1, 1:-1, 1:-1]
        even = cm["even"][1:-1, 1:-1, 1:-1]
        for _ in range(n):
            p = halo_exchange(p, comm)
            p, _ = sor_pass_obstacle_3d(
                p, rhs, odd, ml, c["idx2"], c["idy2"], c["idz2"]
            )
            p = halo_exchange(p, comm)
            p, _ = sor_pass_obstacle_3d(
                p, rhs, even, ml, c["idx2"], c["idy2"], c["idz2"]
            )
            p = neumann_masked_3d(p, cm)
        return p

    def bottom(p, rhs, lvl):
        c = cfg[lvl]
        # declared aggregation boundary (analysis/commcheck census)
        with jax.named_scope("mg_aggregate.obstacle3d"):
            pg = _lax.all_gather(
                p[1:-1, 1:-1, 1:-1], "k", axis=0, tiled=True)
            pg = _lax.all_gather(pg, "j", axis=1, tiled=True)
            pg = _lax.all_gather(pg, "i", axis=2, tiled=True)
            rg = _lax.all_gather(
                rhs[1:-1, 1:-1, 1:-1], "k", axis=0, tiled=True)
            rg = _lax.all_gather(rg, "j", axis=1, tiled=True)
            rg = _lax.all_gather(rg, "i", axis=2, tiled=True)
        pe = neumann_faces_3d(_embed3(pg))
        re = _embed3(rg)
        if bottom_exact is not None:
            pe = bottom_exact(pe, re)
        elif bottom_fft is not None:
            pe = bottom_fft(pe, re)
        else:
            for _ in range(n_coarse):
                pe, _ = sor_pass_obstacle_3d(
                    pe, re, c["odd_g"], c["m"],
                    c["idx2"], c["idy2"], c["idz2"],
                )
                pe, _ = sor_pass_obstacle_3d(
                    pe, re, c["even_g"], c["m"],
                    c["idx2"], c["idy2"], c["idz2"],
                )
                pe = neumann_faces_3d(pe)
        koff = get_offsets("k", c["kl"])
        joff = get_offsets("j", c["jl"])
        ioff = get_offsets("i", c["il"])
        return _lax.dynamic_slice(
            pe, (koff, joff, ioff), (c["kl"] + 2, c["jl"] + 2, c["il"] + 2)
        )

    def vcycle(p, rhs, lvl=0):
        c = cfg[lvl]
        if lvl == len(levels) - 1:
            return bottom(p, rhs, lvl)
        p = smooth(p, rhs, lvl, n_pre)
        p = halo_exchange(p, comm)  # residual reads shard-edge neighbours
        ml = shard_masks_3d(c["m"], c["kl"], c["jl"], c["il"])
        r = obstacle_residual_3d(
            p, rhs, ml, c["idx2"], c["idy2"], c["idz2"]
        )
        r2 = _restrict3(r)
        e2 = vcycle(_embed3(jnp.zeros_like(r2)), _embed3(r2), lvl + 1)
        p = p.at[1:-1, 1:-1, 1:-1].add(
            _prolong3(e2[1:-1, 1:-1, 1:-1]) * ml.p_mask
        )
        cm = ca_masks_3d(c["kl"], c["jl"], c["il"], 1,
                         c["kmax"], c["jmax"], c["imax"], dtype)
        p = neumann_masked_3d(p, cm)
        return smooth(p, rhs, lvl, n_post)

    fine = cfg[0]
    norm = fine["m"].n_fluid
    epssq = eps * eps

    def solve(p, rhs):
        ml = shard_masks_3d(fine["m"], fine["kl"], fine["jl"], fine["il"])

        def cond(c):
            _, res, prev, it = c
            return jnp.logical_and(
                jnp.logical_and(res >= epssq, it < itermax),
                jnp.logical_not(_stalled(prev, res, it, stall_rtol)),
            )

        def body(c):
            p, prev, _, it = c
            p = vcycle(p, rhs)
            p = halo_exchange(p, comm)
            r = obstacle_residual_3d(
                p, rhs, ml, fine["idx2"], fine["idy2"], fine["idz2"]
            )
            res = reduction(jnp.sum(r * r), comm, "sum") / norm
            if _flags.debug():
                # ≙ -DDEBUG Residuum per V-cycle, rank-0 shard only (the
                # -single-device _mg_converge_loop's print, distributed)
                master_print(comm, "{} Residuum: {}", it, res)
            return p, res, prev, it + 1

        p, res, _, it = lax.while_loop(
            cond, body,
            (p, jnp.asarray(1.0, dtype), jnp.asarray(jnp.inf, dtype),
             jnp.asarray(0, jnp.int32)),
        )
        # zero-trip safety; see make_dist_mg_solve_2d
        return halo_exchange(p, comm), res, it

    return solve, bool(sm)
