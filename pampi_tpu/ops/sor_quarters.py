"""Red-black SOR in the QUARTER decomposition — the compressed layout that
works on TPU.

Round 1 measured the obvious compressed red-black layout (two half-width
arrays, one per color) 1.6× SLOWER than the masked checkerboard: packing by
column parity makes the east/west neighbour index depend on ROW parity, and
the per-row lane selects cost more than the masking they remove
(ops/sor_pallas.py module docstring). The fix is to split by BOTH parities —
four dense (J/2, I/2) arrays

    R0[r,c] = p[2r,   2c  ]   red   on even rows
    R1[r,c] = p[2r+1, 2c+1]   red   on odd rows
    B0[r,c] = p[2r,   2c+1]   black on even rows
    B1[r,c] = p[2r+1, 2c  ]   black on odd rows

under which every 5-point neighbour is a UNIFORM shift, verified identities:

    R0: W=B0[c-1] E=B0[c]   S=B1[r-1] N=B1[r]
    R1: W=B1[c]   E=B1[c+1] S=B0[r]   N=B0[r+1]
    B0: W=R0[c]   E=R0[c+1] S=R1[r-1] N=R1[r]
    B1: W=R1[c-1] E=R1[c]   S=R0[r]   N=R0[r+1]

so a half-sweep is two dense, unmasked, all-lanes-productive updates — half
the arithmetic and a third of the shifts of the masked checkerboard (which
computes both laps over every lane and throws half away). The Neumann ghost
refresh becomes FOUR same-index edge-strip copies between quarters (no
shifts): p[0,:]=p[1,:] ⇒ R0[0,:]=B1[0,:], B0[0,:]=R1[0,:]; the top row
j=jmax+1 (odd) ⇒ R1[-1,:]=B0[-1,:], B1[-1,:]=R0[-1,:]; left i=0 ⇒
R0[:,0]=B0[:,0], B1[:,0]=R1[:,0]; right i=imax+1 (odd) ⇒ B0[:,-1]=R0[:,-1],
R1[:,-1]=B1[:,-1] — edge strips clipped to the interior range exactly like
the reference's BC loops (corners untouched, solver.c:157-165).

Requires imax and jmax EVEN (every production grid here is); the arithmetic
keeps the reference's association (e − 2c + w)·idx2 + (n − 2c + s)·idy2
term-for-term, but XLA contracts multiply-adds differently for
differently-structured programs, so equality with the masked jnp path is
ULP-LEVEL (f32 ~4e-7 on O(1) fields, f64 ~1e-15 — tests/test_sor_quarters.py),
not bitwise; the residual summation order differs too. The checkerboard
layout (`tpu_sor_layout checkerboard`) remains the bitwise-oracle mode.

This module: layout transforms + the jnp oracle step. The production Pallas
kernel lives in ops/sor_pallas.py (`make_rb_iter_tblock_quarters`).
"""

from __future__ import annotations

import jax.numpy as jnp


def pack_quarters(p):
    """(J, I) even-shaped array -> (R0, R1, B0, B1) quarter views."""
    assert p.shape[0] % 2 == 0 and p.shape[1] % 2 == 0, p.shape
    return p[0::2, 0::2], p[1::2, 1::2], p[0::2, 1::2], p[1::2, 0::2]


def unpack_quarters(R0, R1, B0, B1):
    J2, I2 = R0.shape
    p = jnp.zeros((2 * J2, 2 * I2), R0.dtype)
    p = p.at[0::2, 0::2].set(R0)
    p = p.at[1::2, 1::2].set(R1)
    p = p.at[0::2, 1::2].set(B0)
    p = p.at[1::2, 0::2].set(B1)
    return p


def neumann_bc_quarters(R0, R1, B0, B1):
    """Ghost refresh, quarter space (see module docstring derivation).
    Interior clipping: bottom/top rows copy columns i ∈ [1, imax] — for the
    even-i quarters (R0, B1) that is c ≥ 1, for odd-i (B0, R1) every c
    except the last (i = imax+1); left/right copy rows j ∈ [1, jmax] —
    even-j quarters (R0, B0) r ≥ 1, odd-j (R1, B1) every r but the last."""
    R0 = R0.at[0, 1:].set(B1[0, 1:])          # p[0,i]=p[1,i], even i
    B0 = B0.at[0, :-1].set(R1[0, :-1])        # p[0,i]=p[1,i], odd i
    R1 = R1.at[-1, :-1].set(B0[-1, :-1])      # p[jmax+1,i]=p[jmax,i], odd i
    B1 = B1.at[-1, 1:].set(R0[-1, 1:])        # p[jmax+1,i]=p[jmax,i], even i
    R0 = R0.at[1:, 0].set(B0[1:, 0])          # p[j,0]=p[j,1], even j
    B1 = B1.at[:-1, 0].set(R1[:-1, 0])        # p[j,0]=p[j,1], odd j
    B0 = B0.at[1:, -1].set(R0[1:, -1])        # p[j,imax+1]=p[j,imax], even j
    R1 = R1.at[:-1, -1].set(B1[:-1, -1])      # p[j,imax+1]=p[j,imax], odd j
    return R0, R1, B0, B1


def _upd(center, rhs, w, e, s, n, factor, idx2, idy2):
    """Reference association (solver.c:205-212): r = rhs − lap; c −= factor·r.
    Returns (updated, r)."""
    r = rhs - ((e - 2.0 * center + w) * idx2 + (n - 2.0 * center + s) * idy2)
    return center - factor * r, r


def rb_iter_quarters(q, rhsq, factor, idx2, idy2):
    """One FULL red-black iteration + Neumann refresh in quarter space.

    q, rhsq: (R0, R1, B0, B1) tuples. Interior masks are rectangular slices
    per quarter (jmax, imax even): R0 interior r≥1,c≥1; R1 r≤-2,c≤-2;
    B0 r≥1,c≤-2; B1 r≤-2,c≥1. Returns (q', sum r² over both half-sweeps)."""
    R0, R1, B0, B1 = q
    F0, F1, G0, G1 = rhsq

    def shift(a, dr, dc):
        return jnp.roll(a, (-dr, -dc), (0, 1))  # out[r,c] = a[r+dr, c+dc]

    # red pass (reads black only)
    u0, r0 = _upd(R0, F0, shift(B0, 0, -1), B0, shift(B1, -1, 0), B1,
                  factor, idx2, idy2)
    R0n = R0.at[1:, 1:].set(u0[1:, 1:])
    u1, r1 = _upd(R1, F1, B1, shift(B1, 0, 1), B0, shift(B0, 1, 0),
                  factor, idx2, idy2)
    R1n = R1.at[:-1, :-1].set(u1[:-1, :-1])
    rsq = jnp.sum(r0[1:, 1:] ** 2) + jnp.sum(r1[:-1, :-1] ** 2)

    # black pass (reads the red pass's updates)
    u2, r2 = _upd(B0, G0, R0n, shift(R0n, 0, 1), shift(R1n, -1, 0), R1n,
                  factor, idx2, idy2)
    B0n = B0.at[1:, :-1].set(u2[1:, :-1])
    u3, r3 = _upd(B1, G1, shift(R1n, 0, -1), R1n, R0n, shift(R0n, 1, 0),
                  factor, idx2, idy2)
    B1n = B1.at[:-1, 1:].set(u3[:-1, 1:])
    rsq = rsq + jnp.sum(r2[1:, :-1] ** 2) + jnp.sum(r3[:-1, 1:] ** 2)

    return neumann_bc_quarters(R0n, R1n, B0n, B1n), rsq
