"""NS-3D staggered-grid ops, branch-free for TPU.

Capability parity with /root/reference/assignment-6/src/solver.c — the 3-D
F/G/H momentum predictor (computeFG:606-769), 6-face × 4-kind BCs
(setBoundaryConditions:364-577), special BCs (:579-604), CFL timestep
(:340-362), projection (adaptUV:826-853), RHS (computeRHS:145-173),
interior-only pressure normalization (:312-338).

Arrays are (kmax+2, jmax+2, imax+2), layout [k, j, i]; u on east faces,
v on north faces, w on back faces, p at centers.

Replicated reference quirks (documented, required for oracle parity):
- dvwdz in the G predictor uses V(i,j,k+1) in BOTH flux halves and both
  γ-terms (solver.c:712-723) where the symmetric scheme would use
  V(i,j,k-1) in the second — we reproduce the reference's arithmetic.
- dcavity lid skips the last interior i AND k (loops `< imaxLocal`,
  `< kmaxLocal`, solver.c:587-594).
- canal inflow is uniform U=2.0, not the 2-D parabola (solver.c:595-602).
"""

from __future__ import annotations

import jax.numpy as jnp

NOSLIP, SLIP, OUTFLOW, PERIODIC = 1, 2, 3, 4


def V3(a, dk=0, dj=0, di=0):
    """Interior view shifted by (dk, dj, di) — the (i±1, j±1, k±1) stencil
    accessor over the whole interior at once."""
    K, J, I = a.shape
    return a[1 + dk : K - 1 + dk, 1 + dj : J - 1 + dj, 1 + di : I - 1 + di]


def fgh_predictor_terms(u, v, w, dt, re, gx, gy, gz, gamma, dx, dy, dz,
                        sh=V3):
    """The 3-D momentum-predictor arithmetic (computeFG, solver.c:639-769) —
    the SINGLE home of the formula, shared by the jnp path
    (`compute_fgh_interior`, sh=V3 interior views) and the fused Pallas
    step-phase kernel (ops/ns3d_fused.py, a roll-based window shift).
    `sh(a, dk=, dj=, di=)` returns the (dk, dj, di)-shifted view of `a`;
    both accessors deliver the same neighbour VALUES at every cell whose
    neighbours are real, so outputs agree bitwise there."""
    idx, idy, idz = 1.0 / dx, 1.0 / dy, 1.0 / dz
    inv_re = 1.0 / re

    uc = sh(u)
    vc = sh(v)
    wc = sh(w)
    u_ip, u_im = sh(u, di=1), sh(u, di=-1)
    u_jp, u_jm = sh(u, dj=1), sh(u, dj=-1)
    u_kp, u_km = sh(u, dk=1), sh(u, dk=-1)
    v_ip, v_im = sh(v, di=1), sh(v, di=-1)
    v_jp, v_jm = sh(v, dj=1), sh(v, dj=-1)
    v_kp, v_km = sh(v, dk=1), sh(v, dk=-1)
    w_ip, w_im = sh(w, di=1), sh(w, di=-1)
    w_jp, w_jm = sh(w, dj=1), sh(w, dj=-1)
    w_kp, w_km = sh(w, dk=1), sh(w, dk=-1)
    u_im_jp = sh(u, dj=1, di=-1)
    u_im_kp = sh(u, dk=1, di=-1)
    v_jm_ip = sh(v, dj=-1, di=1)
    v_jm_kp = sh(v, dk=1, dj=-1)
    w_km_ip = sh(w, dk=-1, di=1)
    w_km_jp = sh(w, dk=-1, dj=1)

    ab = jnp.abs

    # ---- F ----
    du2dx = idx * 0.25 * (
        (uc + u_ip) * (uc + u_ip) - (uc + u_im) * (uc + u_im)
    ) + gamma * idx * 0.25 * (
        ab(uc + u_ip) * (uc - u_ip) + ab(uc + u_im) * (uc - u_im)
    )
    duvdy = idy * 0.25 * (
        (vc + v_ip) * (uc + u_jp) - (v_jm + v_jm_ip) * (uc + u_jm)
    ) + gamma * idy * 0.25 * (
        ab(vc + v_ip) * (uc - u_jp) + ab(v_jm + v_jm_ip) * (uc - u_jm)
    )
    duwdz = idz * 0.25 * (
        (wc + w_ip) * (uc + u_kp) - (w_km + w_km_ip) * (uc + u_km)
    ) + gamma * idz * 0.25 * (
        ab(wc + w_ip) * (uc - u_kp) + ab(w_km + w_km_ip) * (uc - u_km)
    )
    lap_u = (
        idx * idx * (u_ip - 2.0 * uc + u_im)
        + idy * idy * (u_jp - 2.0 * uc + u_jm)
        + idz * idz * (u_kp - 2.0 * uc + u_km)
    )
    f_int = uc + dt * (inv_re * lap_u - du2dx - duvdy - duwdz + gx)

    # ---- G ----
    duvdx = idx * 0.25 * (
        (uc + u_jp) * (vc + v_ip) - (u_im + u_im_jp) * (vc + v_im)
    ) + gamma * idx * 0.25 * (
        ab(uc + u_jp) * (vc - v_ip) + ab(u_im + u_im_jp) * (vc - v_im)
    )
    dv2dy = idy * 0.25 * (
        (vc + v_jp) * (vc + v_jp) - (vc + v_jm) * (vc + v_jm)
    ) + gamma * idy * 0.25 * (
        ab(vc + v_jp) * (vc - v_jp) + ab(vc + v_jm) * (vc - v_jm)
    )
    # reference quirk: v_kp in BOTH halves and both γ-terms (solver.c:712-723)
    dvwdz = idz * 0.25 * (
        (wc + w_jp) * (vc + v_kp) - (w_km + w_km_jp) * (vc + v_kp)
    ) + gamma * idz * 0.25 * (
        ab(wc + w_jp) * (vc - v_kp) + ab(w_km + w_km_jp) * (vc - v_kp)
    )
    lap_v = (
        idx * idx * (v_ip - 2.0 * vc + v_im)
        + idy * idy * (v_jp - 2.0 * vc + v_jm)
        + idz * idz * (v_kp - 2.0 * vc + v_km)
    )
    g_int = vc + dt * (inv_re * lap_v - duvdx - dv2dy - dvwdz + gy)

    # ---- H ----
    duwdx = idx * 0.25 * (
        (uc + u_kp) * (wc + w_ip) - (u_im + u_im_kp) * (wc + w_im)
    ) + gamma * idx * 0.25 * (
        ab(uc + u_kp) * (wc - w_ip) + ab(u_im + u_im_kp) * (wc - w_im)
    )
    dvwdy = idy * 0.25 * (
        (vc + v_kp) * (wc + w_jp) - (v_jm_kp + v_jm) * (wc + w_jm)
    ) + gamma * idy * 0.25 * (
        ab(vc + v_kp) * (wc - w_jp) + ab(v_jm_kp + v_jm) * (wc - w_jm)
    )
    dw2dz = idz * 0.25 * (
        (wc + w_kp) * (wc + w_kp) - (wc + w_km) * (wc + w_km)
    ) + gamma * idz * 0.25 * (
        ab(wc + w_kp) * (wc - w_kp) + ab(wc + w_km) * (wc - w_km)
    )
    lap_w = (
        idx * idx * (w_ip - 2.0 * wc + w_im)
        + idy * idy * (w_jp - 2.0 * wc + w_jm)
        + idz * idz * (w_kp - 2.0 * wc + w_km)
    )
    h_int = wc + dt * (inv_re * lap_w - duwdx - dvwdy - dw2dz + gz)
    return f_int, g_int, h_int


def compute_fgh_interior(u, v, w, dt, re, gx, gy, gz, gamma, dx, dy, dz):
    """3-D momentum predictor interior (computeFG, solver.c:639-769); the
    arithmetic lives in `fgh_predictor_terms` (shared with the fused
    kernel)."""
    f_int, g_int, h_int = fgh_predictor_terms(
        u, v, w, dt, re, gx, gy, gz, gamma, dx, dy, dz
    )
    f = jnp.zeros_like(u).at[1:-1, 1:-1, 1:-1].set(f_int)
    g = jnp.zeros_like(v).at[1:-1, 1:-1, 1:-1].set(g_int)
    h = jnp.zeros_like(w).at[1:-1, 1:-1, 1:-1].set(h_int)
    return f, g, h


def apply_fgh_wall_fixups(f, g, h, u, v, w):
    """F=U on left/right, G=V on bottom/top, H=W on front/back walls
    (solver.c:771-823) — ungated single-device composition."""
    f = f.at[1:-1, 1:-1, 0].set(u[1:-1, 1:-1, 0])
    f = f.at[1:-1, 1:-1, -2].set(u[1:-1, 1:-1, -2])
    g = g.at[1:-1, 0, 1:-1].set(v[1:-1, 0, 1:-1])
    g = g.at[1:-1, -2, 1:-1].set(v[1:-1, -2, 1:-1])
    h = h.at[0, 1:-1, 1:-1].set(w[0, 1:-1, 1:-1])
    h = h.at[-2, 1:-1, 1:-1].set(w[-2, 1:-1, 1:-1])
    return f, g, h


def compute_fgh(u, v, w, dt, re, gx, gy, gz, gamma, dx, dy, dz):
    f, g, h = compute_fgh_interior(u, v, w, dt, re, gx, gy, gz, gamma, dx, dy, dz)
    return apply_fgh_wall_fixups(f, g, h, u, v, w)


def rhs_terms_3d(f, g, h, dt, dx, dy, dz, sh=V3):
    """3-D RHS = div(F,G,H)/dt arithmetic (shared with the fused kernel,
    see fgh_predictor_terms for the `sh` contract)."""
    return (
        (sh(f) - sh(f, di=-1)) / dx
        + (sh(g) - sh(g, dj=-1)) / dy
        + (sh(h) - sh(h, dk=-1)) / dz
    ) * (1.0 / dt)


def compute_rhs(f, g, h, dt, dx, dy, dz):
    """RHS = div(F,G,H)/dt (computeRHS, solver.c:163-172)."""
    rhs_int = rhs_terms_3d(f, g, h, dt, dx, dy, dz)
    return jnp.zeros_like(f).at[1:-1, 1:-1, 1:-1].set(rhs_int)


def adapt_terms_3d(f, g, h, p, dt, dx, dy, dz, sh=V3):
    """3-D projection arithmetic (shared with the fused kernel)."""
    u_new = sh(f) - (sh(p, di=1) - sh(p)) * (dt / dx)
    v_new = sh(g) - (sh(p, dj=1) - sh(p)) * (dt / dy)
    w_new = sh(h) - (sh(p, dk=1) - sh(p)) * (dt / dz)
    return u_new, v_new, w_new


def adapt_uvw(u, v, w, f, g, h, p, dt, dx, dy, dz):
    """Projection (adaptUV, solver.c:845-852)."""
    u_new, v_new, w_new = adapt_terms_3d(f, g, h, p, dt, dx, dy, dz)
    u = u.at[1:-1, 1:-1, 1:-1].set(u_new)
    v = v.at[1:-1, 1:-1, 1:-1].set(v_new)
    w = w.at[1:-1, 1:-1, 1:-1].set(w_new)
    return u, v, w


# face descriptors: (name, axis, side). axis: 0=k, 1=j, 2=i.
FACES = {
    "top": (1, "hi"),
    "bottom": (1, "lo"),
    "left": (2, "lo"),
    "right": (2, "hi"),
    "front": (0, "lo"),
    "back": (0, "hi"),
}


def _plane(axis, pos):
    """Index tuple selecting the `pos` plane along axis, tangential [1:-1]."""
    idx = [slice(1, -1)] * 3
    idx[axis] = pos
    return tuple(idx)


def set_boundary_conditions_3d(u, v, w, bcs, flags=None):
    """6-face × 4-kind BC application (setBoundaryConditions:364-577).
    bcs is a dict face-name -> kind (insertion order = the reference's
    application order: top, bottom, left, right, front, back); kinds are
    static config, resolved at trace time. Staggered positions per the
    reference's write sets: on a LO face the normal component AND the
    tangential ghosts live at index 0 (v₀ sits on the bottom wall); on a HI
    face the normal lives at -2 (on the wall) and tangential ghosts at -1.
    NOSLIP mirrors tangential ghosts negatively, SLIP positively, OUTFLOW
    copies everything from the next-inner plane; PERIODIC is a no-op as in
    the reference.

    flags: optional dict face-name -> boolean predicate gating each face's
    writes (≙ the commIsBoundary guards); None applies every face
    (single-device). All write sets are tangentially clipped to [1:-1], so
    gated faces compose without clobbering each other's planes."""
    fields = {0: w, 1: v, 2: u}  # normal component per axis

    def write(arr, idx, val, face):
        if flags is not None:
            val = jnp.where(flags[face], val, arr[idx])
        return arr.at[idx].set(val)

    for face, kind in bcs.items():
        axis, side = FACES[face]
        if side == "lo":
            ghost_pos, wall_pos, step = 0, 0, 1
        else:
            ghost_pos, wall_pos, step = -1, -2, -1
        ghost = _plane(axis, ghost_pos)
        ghost_in = _plane(axis, ghost_pos + step)
        wall = _plane(axis, wall_pos)
        wall_in = _plane(axis, wall_pos + step)
        normal = fields[axis]
        t_axes = [a for a in (0, 1, 2) if a != axis]
        if kind == NOSLIP:
            fields[axis] = write(normal, wall, jnp.zeros_like(normal[wall]), face)
            for a in t_axes:
                fields[a] = write(fields[a], ghost, -fields[a][ghost_in], face)
        elif kind == SLIP:
            fields[axis] = write(normal, wall, jnp.zeros_like(normal[wall]), face)
            for a in t_axes:
                fields[a] = write(fields[a], ghost, fields[a][ghost_in], face)
        elif kind == OUTFLOW:
            fields[axis] = write(normal, wall, normal[wall_in], face)
            for a in t_axes:
                fields[a] = write(fields[a], ghost, fields[a][ghost_in], face)
        elif kind == PERIODIC:
            pass
    return fields[2], fields[1], fields[0]


def set_special_bc_dcavity_3d(u):
    """Lid U(i, jmax+1, k) = 2 − U(i, jmax, k), skipping the LAST interior i
    and k (reference loop bounds `< imaxLocal`/`< kmaxLocal`, solver.c:587-594)."""
    return u.at[1:-2, -1, 1:-2].set(2.0 - u[1:-2, -2, 1:-2])


def set_special_bc_canal_3d(u):
    """Uniform inflow U(0, j, k) = 2.0 (solver.c:595-602)."""
    return u.at[1:-1, 1:-1, 0].set(2.0)


def max_element(m):
    """max |m| over the FULL local array incl. ghosts (solver.c:299-310)."""
    return jnp.max(jnp.abs(m))


def cfl_dt_3d(umax, vmax, wmax, dt_bound, dx, dy, dz, tau):
    """3-D CFL scalar math given the velocity maxima (see ops/ns2d.cfl_dt
    for the fused-path sharing rationale)."""
    inf = jnp.asarray(jnp.inf, umax.dtype)
    dt = jnp.minimum(
        dt_bound,
        jnp.minimum(
            jnp.where(umax > 0, dx / umax, inf),
            jnp.minimum(
                jnp.where(vmax > 0, dy / vmax, inf),
                jnp.where(wmax > 0, dz / wmax, inf),
            ),
        ),
    )
    return dt * tau


def compute_timestep_3d(u, v, w, dt_bound, dx, dy, dz, tau):
    """3-D CFL (computeTimestep, solver.c:340-362)."""
    return cfl_dt_3d(
        max_element(u), max_element(v), max_element(w),
        dt_bound, dx, dy, dz, tau,
    )


def normalize_pressure_3d(p, imax, jmax, kmax):
    """Interior-only mean subtract, normalized by imax·jmax·kmax
    (normalizePressure, solver.c:312-338 — NOTE: unlike the 2-D sequential
    variant, ghosts are excluded). API-parity function: the reference defines
    it but its 3-D main loop never calls it (main.c:50-67) — same here."""
    avg = jnp.sum(p[1:-1, 1:-1, 1:-1]) / float(imax * jmax * kmax)
    return p.at[1:-1, 1:-1, 1:-1].add(-avg)
