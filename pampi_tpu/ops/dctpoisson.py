"""Direct (non-iterative) Neumann-Poisson solve by DCT diagonalization,
executed as MXU matmuls — a beyond-parity fast solver.

The pressure-Poisson problem every solver in this framework (and the
reference) iterates on is a CONSTANT-coefficient 5/7-point Laplacian on a
uniform cell-centered grid with homogeneous-Neumann ghost-copy BCs
(/root/reference/assignment-4/src/solver.c:157-165, assignment-6/src/
solver.c:233-279). That operator is diagonalized exactly by the DCT-II
basis: eigenvectors cos(πk(2i+1)/(2N)) per axis, eigenvalues
(2cos(πk/N) − 2)/h². So the DISCRETE solution is

    p = C^T [ (C rhs C^T …) / (λx ⊕ λy ⊕ λz) ] C …   (zero mode -> 0)

computed to machine precision in ONE application — no convergence loop.

TPU-first design: this chip's XLA backend has no FFT at all
(jnp.fft -> UNIMPLEMENTED), and for the grid sizes here an FFT would be the
wrong tool anyway — the orthonormal DCT matrix is a dense (N, N) constant,
so each transform is a single MXU matmul (tensordot along the axis), the
thing the hardware is built for. At 4096² the whole solve is four
4096-matmuls plus an elementwise divide.

Used two ways:
- `tpu_solver fft` — direct whole-grid pressure solve (models dispatch);
  `it` reports 1, `res` is the honestly-computed residual of the returned
  field (f32 roundoff-level, far below any practical eps).
- multigrid's coarsest level (ops/multigrid.py): the bottom problem is
  solved EXACTLY instead of smoothed, which both removes the odd-extent
  weakness (a 25² bottom is no worse than a 4²) and eliminates the long
  unrolled coarse loops.

The all-Neumann operator is singular (constants); the k=0 mode is set to
zero — the standard compatibility projection, matching the "solutions agree
mod constants" semantics every test in this repo already uses.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


def dct2_matrix(N: int) -> np.ndarray:
    """Orthonormal DCT-II analysis matrix D (k, i): applying D @ x gives the
    DCT-II coefficients of x; D is orthogonal so D.T is the inverse."""
    k = np.arange(N)[:, None]
    i = np.arange(N)[None, :]
    d = np.cos(np.pi * k * (2 * i + 1) / (2.0 * N))
    d *= np.sqrt(2.0 / N)
    d[0] *= np.sqrt(0.5)
    return d


def neumann_eigenvalues(N: int, h: float) -> np.ndarray:
    """Eigenvalues of the 1-D cell-centered Neumann Laplacian (ghost-copy
    BCs) in the DCT-II basis: λ_k = (2cos(πk/N) − 2)/h²; λ_0 = 0."""
    k = np.arange(N)
    return (2.0 * np.cos(np.pi * k / N) - 2.0) / (h * h)


def _apply(mat, x, axis):
    """Contract `mat` (K, N) with `x` along `axis` — one MXU matmul."""
    y = jnp.tensordot(mat, x, axes=[[1], [axis]])
    return jnp.moveaxis(y, 0, axis)


def poisson_dct_2d(rhs_int, dx: float, dy: float):
    """Exact interior solve of lap(p) = rhs (Neumann, zero-mean mode).
    rhs_int: (jmax, imax) interior array; returns p interior."""
    J, I = rhs_int.shape
    dt = rhs_int.dtype
    Dj = jnp.asarray(dct2_matrix(J), dt)
    Di = jnp.asarray(dct2_matrix(I), dt)
    lj = neumann_eigenvalues(J, dy)
    li = neumann_eigenvalues(I, dx)
    denom = jnp.asarray(lj[:, None] + li[None, :], dt)
    h = _apply(Di, _apply(Dj, rhs_int, 0), 1)
    ph = jnp.where(denom != 0, h / jnp.where(denom != 0, denom, 1.0), 0.0)
    return _apply(Di.T, _apply(Dj.T, ph, 0), 1)


def poisson_dct_3d(rhs_int, dx: float, dy: float, dz: float):
    """3-D twin: rhs_int (kmax, jmax, imax) -> p interior."""
    K, J, I = rhs_int.shape
    dt = rhs_int.dtype
    Dk = jnp.asarray(dct2_matrix(K), dt)
    Dj = jnp.asarray(dct2_matrix(J), dt)
    Di = jnp.asarray(dct2_matrix(I), dt)
    lk = neumann_eigenvalues(K, dz)
    lj = neumann_eigenvalues(J, dy)
    li = neumann_eigenvalues(I, dx)
    denom = jnp.asarray(
        lk[:, None, None] + lj[None, :, None] + li[None, None, :], dt
    )
    h = _apply(Di, _apply(Dj, _apply(Dk, rhs_int, 0), 1), 2)
    ph = jnp.where(denom != 0, h / jnp.where(denom != 0, denom, 1.0), 0.0)
    return _apply(Di.T, _apply(Dj.T, _apply(Dk.T, ph, 0), 1), 2)


def _check_direct_dtype(dtype) -> None:
    """The direct solve returns after ONE application — there is no
    convergence loop to absorb arithmetic error, so half precision would
    silently break the eps-stopping contract the iterative solvers enforce.
    f32/f64 round-trip error stays orders of magnitude below any practical
    eps (see tests); bf16 is rejected at build time."""
    if jnp.dtype(dtype).itemsize < 4:
        raise ValueError(
            "tpu_solver fft needs float32/float64 (a one-shot direct solve "
            "cannot iterate bf16 error away); use sor or mg for bfloat16"
        )


def make_dct_solve_2d(imax, jmax, dx, dy, dtype):
    """Solve-contract wrapper `(p_ext, rhs_ext) -> (p_ext, res, it)`:
    direct solve, it = 1, res = the returned field's true residual
    normalized like the iterative solvers (Σr²/(imax·jmax)) — REPORTED but
    not looped on (there is nothing to iterate); callers inherit roundoff-
    level residuals, far below any practical eps at f32/f64."""
    from .sor import _interior_residual, neumann_bc

    _check_direct_dtype(dtype)

    idx2, idy2 = 1.0 / (dx * dx), 1.0 / (dy * dy)
    norm = float(imax * jmax)

    def solve(p, rhs):
        del p  # direct: the previous iterate is not needed
        sol = poisson_dct_2d(rhs[1:-1, 1:-1], dx, dy)
        pn = jnp.zeros((jmax + 2, imax + 2), dtype).at[1:-1, 1:-1].set(sol)
        pn = neumann_bc(pn)
        r = _interior_residual(pn, rhs, idx2, idy2)
        return pn, jnp.sum(r * r) / norm, jnp.asarray(1, jnp.int32)

    return solve


def make_dct_solve_3d(imax, jmax, kmax, dx, dy, dz, dtype):
    from ..models.ns3d import interior_residual_3d, neumann_faces_3d

    _check_direct_dtype(dtype)

    idx2 = 1.0 / (dx * dx)
    idy2 = 1.0 / (dy * dy)
    idz2 = 1.0 / (dz * dz)
    norm = float(imax * jmax * kmax)

    def solve(p, rhs):
        del p
        sol = poisson_dct_3d(rhs[1:-1, 1:-1, 1:-1], dx, dy, dz)
        pn = jnp.zeros((kmax + 2, jmax + 2, imax + 2), dtype)
        pn = pn.at[1:-1, 1:-1, 1:-1].set(sol)
        pn = neumann_faces_3d(pn)
        r = interior_residual_3d(pn, rhs, idx2, idy2, idz2)
        return pn, jnp.sum(r * r) / norm, jnp.asarray(1, jnp.int32)

    return solve


# ----------------------------------------------------------------------
# Distributed direct solve (call INSIDE shard_map): the DCT along a sharded
# axis is a COLLECTIVE MATMUL — each shard contracts its slice of the
# orthogonal matrix with its local block (a full-length partial sum), and a
# psum_scatter along the mesh axis both reduces the partials and hands every
# shard exactly its block of the transformed array. Two collectives per
# transform, O(N²/P) MXU work per shard — the canonical TPU sharded-matmul
# pattern applied to a fast Poisson solver.
# ----------------------------------------------------------------------


def _dist_apply(mat, x, axis: int, axis_name: str, nper: int):
    """Contract the (N, N) constant `mat` along `x`'s (possibly sharded)
    array axis. nper == 1 falls back to the local matmul."""
    from jax import lax

    if nper == 1:
        return _apply(mat, x, axis)
    n_loc = x.shape[axis]
    c = lax.axis_index(axis_name)
    cols = lax.dynamic_slice_in_dim(mat, c * n_loc, n_loc, axis=1)
    partial = jnp.moveaxis(
        jnp.tensordot(cols, x, axes=[[1], [axis]]), 0, axis
    )
    return lax.psum_scatter(
        partial, axis_name, scatter_dimension=axis, tiled=True
    )


def _own_eigs(eigs_np, n_loc: int, axis_name: str, nper: int, dtype):
    from jax import lax

    e = jnp.asarray(eigs_np, dtype)
    if nper == 1:
        return e
    c = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(e, c * n_loc, n_loc, axis=0)


def make_dist_dct_solve_2d(comm, imax, jmax, jl, il, dx, dy, dtype):
    """Distributed fft solve (shard_map kernel side): same contract as the
    distributed iterative solves — `(p_ext, rhs_ext) -> (p_ext, res, it)` on
    halo-1 extended local blocks; it = 1."""
    from ..parallel.comm import halo_exchange, reduction
    from ..parallel.stencil2d import ca_masks, neumann_masked
    from .sor import _interior_residual

    _check_direct_dtype(dtype)
    Pj = comm.axis_size("j")
    Pi = comm.axis_size("i")
    Dj = jnp.asarray(dct2_matrix(jmax), dtype)
    Di = jnp.asarray(dct2_matrix(imax), dtype)
    lj = neumann_eigenvalues(jmax, dy)
    li = neumann_eigenvalues(imax, dx)
    idx2, idy2 = 1.0 / (dx * dx), 1.0 / (dy * dy)
    norm = float(imax * jmax)

    def solve(p, rhs):
        del p
        r = rhs[1:-1, 1:-1]
        h = _dist_apply(Dj, r, 0, "j", Pj)
        h = _dist_apply(Di, h, 1, "i", Pi)
        denom = (
            _own_eigs(lj, jl, "j", Pj, dtype)[:, None]
            + _own_eigs(li, il, "i", Pi, dtype)[None, :]
        )
        ph = jnp.where(denom != 0, h / jnp.where(denom != 0, denom, 1.0), 0.0)
        sol = _dist_apply(Dj.T, ph, 0, "j", Pj)
        sol = _dist_apply(Di.T, sol, 1, "i", Pi)
        pn = jnp.zeros((jl + 2, il + 2), dtype).at[1:-1, 1:-1].set(sol)
        pn = halo_exchange(pn, comm)
        pn = neumann_masked(pn, ca_masks(jl, il, 1, jmax, imax, dtype))
        rr = _interior_residual(pn, rhs, idx2, idy2)
        res = reduction(jnp.sum(rr * rr), comm, "sum") / norm
        return pn, res, jnp.asarray(1, jnp.int32)

    return solve


def make_dist_dct_solve_3d(comm, imax, jmax, kmax, kl, jl, il,
                           dx, dy, dz, dtype):
    """3-D twin of make_dist_dct_solve_2d."""
    from ..models.ns3d import interior_residual_3d
    from ..parallel.comm import halo_exchange, reduction
    from ..parallel.stencil3d import ca_masks_3d, neumann_masked_3d

    _check_direct_dtype(dtype)
    Pk = comm.axis_size("k")
    Pj = comm.axis_size("j")
    Pi = comm.axis_size("i")
    Dk = jnp.asarray(dct2_matrix(kmax), dtype)
    Dj = jnp.asarray(dct2_matrix(jmax), dtype)
    Di = jnp.asarray(dct2_matrix(imax), dtype)
    lk = neumann_eigenvalues(kmax, dz)
    lj = neumann_eigenvalues(jmax, dy)
    li = neumann_eigenvalues(imax, dx)
    idx2 = 1.0 / (dx * dx)
    idy2 = 1.0 / (dy * dy)
    idz2 = 1.0 / (dz * dz)
    norm = float(imax * jmax * kmax)

    def solve(p, rhs):
        del p
        r = rhs[1:-1, 1:-1, 1:-1]
        h = _dist_apply(Dk, r, 0, "k", Pk)
        h = _dist_apply(Dj, h, 1, "j", Pj)
        h = _dist_apply(Di, h, 2, "i", Pi)
        denom = (
            _own_eigs(lk, kl, "k", Pk, dtype)[:, None, None]
            + _own_eigs(lj, jl, "j", Pj, dtype)[None, :, None]
            + _own_eigs(li, il, "i", Pi, dtype)[None, None, :]
        )
        ph = jnp.where(denom != 0, h / jnp.where(denom != 0, denom, 1.0), 0.0)
        sol = _dist_apply(Dk.T, ph, 0, "k", Pk)
        sol = _dist_apply(Dj.T, sol, 1, "j", Pj)
        sol = _dist_apply(Di.T, sol, 2, "i", Pi)
        pn = jnp.zeros((kl + 2, jl + 2, il + 2), dtype)
        pn = pn.at[1:-1, 1:-1, 1:-1].set(sol)
        pn = halo_exchange(pn, comm)
        pn = neumann_masked_3d(
            pn, ca_masks_3d(kl, jl, il, 1, kmax, jmax, imax, dtype)
        )
        rr = interior_residual_3d(pn, rhs, idx2, idy2, idz2)
        res = reduction(jnp.sum(rr * rr), comm, "sum") / norm
        return pn, res, jnp.asarray(1, jnp.int32)

    return solve
