"""3-D red-black SOR in the OCTANT decomposition — the 3-D form of the
quarter layout (ops/sor_quarters.py).

Split the (K, J, I) grid by the parity of ALL THREE indices into eight dense
(K/2, J/2, I/2) arrays, keyed by bits (pk, pj, pi):

    O[pk,pj,pi][s, r, c] = p[2s + pk, 2r + pj, 2c + pi]

The 3-D checkerboard colour is (k + j + i) % 2 = (pk + pj + pi) % 2, so each
colour is exactly four octants, and every 7-point neighbour lives in the
octant with ONE parity bit flipped, at a row-parity-INDEPENDENT uniform
index: along an axis with parity bit b,

    b = 0:  coord−1 → partner[idx − 1],  coord+1 → partner[idx]
    b = 1:  coord−1 → partner[idx],      coord+1 → partner[idx + 1]

(the same identity as the 2-D quarters, once per axis). A half-sweep is four
dense, unmasked (up to rectangular edge clipping), all-lanes-productive
updates; per sub-update only the three "shifted" neighbours move data, so a
full iteration does 12 one-eighth-size shifts (= 1.5 full-array
equivalents) against the masked checkerboard kernel's 12 full-size laps
rolls + 6 BC rolls, and none of the lanes compute thrown-away colour.

The 6-face Neumann refresh becomes 24 SAME-INDEX plane copies between
partner octants (no shifts): the ghost plane k=0 (even) lives in the four
pk=0 octants at s=0 and copies from the pk=1 partners at s=0 (grid k=1);
the hi face k=kmax+1 (odd, kmax even) lives in the pk=1 octants at s=−1 and
copies from pk=0 at s=−1 (grid k=kmax); same per axis. Tangential clipping
to the interior (reference solver.c BC loops): parity-0 axes drop index 0,
parity-1 axes drop the last index — faces never write edges/corners, so
the 24 copies are disjoint and order-free.

Pass order matches the reference's 3-D sweep (assignment-6/src/solver.c:
203-231 and models/ns3d.make_pressure_solve_3d): ODD parity first, then
even. Requires even imax/jmax/kmax. Arithmetic keeps the reference
association ((e−2c+w)·idx2 + (n−2c+s)·idy2 + (b−2c+f)·idz2) term-for-term;
equality with the masked jnp path is ulp-level (compiler fma/fusion
association — see ops/sor_quarters.py), with the checkerboard layout
remaining the bitwise-oracle mode.

This module: layout transforms + the jnp oracle. The Pallas kernel lives in
ops/sor3d_pallas.py (`make_rb_iter_tblock_3d_octants`).
"""

from __future__ import annotations

import jax.numpy as jnp

BITS = [(a, b, c) for a in (0, 1) for b in (0, 1) for c in (0, 1)]
ODD = [b for b in BITS if sum(b) % 2 == 1]   # first half-sweep (reference)
EVEN = [b for b in BITS if sum(b) % 2 == 0]  # second half-sweep


def _flip(bits, axis):
    out = list(bits)
    out[axis] = 1 - out[axis]
    return tuple(out)


def pack_octants(p):
    """(K, J, I) even-shaped array -> dict bits -> (K/2, J/2, I/2)."""
    assert all(d % 2 == 0 for d in p.shape), p.shape
    return {b: p[b[0]::2, b[1]::2, b[2]::2] for b in BITS}


def unpack_octants(octs):
    K2, J2, I2 = octs[(0, 0, 0)].shape
    p = jnp.zeros((2 * K2, 2 * J2, 2 * I2), octs[(0, 0, 0)].dtype)
    for b, q in octs.items():
        p = p.at[b[0]::2, b[1]::2, b[2]::2].set(q)
    return p


def interior_slices(bits):
    """Rectangular interior of an octant: parity-0 axes drop index 0 (the
    ghost plane k/j/i = 0), parity-1 axes drop the last (ghost = max+1)."""
    return tuple(slice(1, None) if b == 0 else slice(0, -1) for b in bits)


def _shift(a, axis, d):
    """out[idx] = a[idx + d] (zero wrap-around contributions are masked or
    clipped away by the callers)."""
    return jnp.roll(a, -d, axis)


def neighbours(octs, bits):
    """(w, e, s, n, f, bk) neighbour arrays for octant `bits` — uniform
    shifts per the module-docstring identity."""

    def ax_pair(axis):
        partner = octs[_flip(bits, axis)]
        if bits[axis] == 0:
            return _shift(partner, axis, -1), partner   # coord−1, coord+1
        return partner, _shift(partner, axis, 1)

    f, bk = ax_pair(0)
    s, n = ax_pair(1)
    w, e = ax_pair(2)
    return w, e, s, n, f, bk


def neumann_bc_octants(octs):
    """The 24 same-index ghost-plane copies (6 faces × 4 octants each)."""
    out = dict(octs)
    for axis in range(3):
        for hi in (False, True):
            plane = -1 if hi else 0
            for bits in BITS:
                if bits[axis] != (1 if hi else 0):
                    continue
                src = out[_flip(bits, axis)]
                sl = list(interior_slices(bits))
                sl[axis] = plane
                sl = tuple(sl)
                out[bits] = out[bits].at[sl].set(src[sl])
    return out


def rb_iter_octants(octs, rhs_octs, factor, idx2, idy2, idz2):
    """One FULL 3-D red-black iteration (odd pass, even pass, Neumann
    refresh) in octant space. Returns (octs', sum r² over both passes)."""

    def half_pass(octs, group):
        out = dict(octs)
        rsq = jnp.zeros((), octs[(0, 0, 0)].dtype)
        for bits in group:
            c = octs[bits]
            w, e, s, n, f, bk = neighbours(out, bits)
            r = rhs_octs[bits] - (
                (e - 2.0 * c + w) * idx2
                + (n - 2.0 * c + s) * idy2
                + (bk - 2.0 * c + f) * idz2
            )
            sl = interior_slices(bits)
            out[bits] = c.at[sl].set((c - factor * r)[sl])
            rsq = rsq + jnp.sum(r[sl] ** 2)
        return out, rsq

    # neighbours() must see the CURRENT state: within a half-sweep the
    # updated octants are the OTHER colour's inputs only, so passing `out`
    # (above) is safe — same-colour octants never read each other.
    octs, r_odd = half_pass(octs, ODD)
    octs, r_evn = half_pass(octs, EVEN)
    return neumann_bc_octants(octs), r_odd + r_evn
