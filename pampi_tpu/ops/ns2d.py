"""NS-2D staggered-grid ops: momentum predictor, boundary conditions, CFL
timestep, projection — branch-free and fully vectorized for TPU.

Capability parity with the reference's sequential nusif-solver
(/root/reference/assignment-5/sequential/src/solver.c), the numerical ground
truth for every distributed variant (SURVEY.md §3.5). Each function cites the
reference routine whose arithmetic it reproduces. Arrays are (jmax+2, imax+2),
layout [j, i]; u lives on east faces, v on north faces, p at centers (the
reference's staggered layout).

TPU-first design notes:
- The reference's per-cell double loops become whole-interior slice algebra;
  XLA fuses the ~30-term F/G predictor into one pass over u/v.
- The 4-kind × 4-wall BC switch ladders (solver.c:236-337) are dispatched at
  TRACE time (bc kinds are static config), so the compiled step has zero
  control flow — each wall is a fixed strip update.
"""

from __future__ import annotations

import jax.numpy as jnp

NOSLIP, SLIP, OUTFLOW, PERIODIC = 1, 2, 3, 4


def compute_fg(u, v, dt, re, gx, gy, gamma, dx, dy):
    """Momentum predictor F,G (computeFG, solver.c:360-435) INCLUDING the
    wall fixups — the single-device composition."""
    f, g = compute_fg_interior(u, v, dt, re, gx, gy, gamma, dx, dy)
    return apply_fg_wall_fixups(f, g, u, v)


def _interior_mask(shape):
    """Static interior-select mask: True on [1:-1, 1:-1]. The full-array
    roll+where formulation below replaces interior dynamic-update-slices —
    profiled at 4096² each DUS costs a full HBM pass (~0.57 ms) that the
    where-select fuses into the producer for free; values at interior
    cells are BITWISE identical (same operands, same op order), edges keep
    the old array (or zero) exactly as the at[].set forms did."""
    j = jnp.zeros((shape[0], 1), bool).at[1:-1].set(True)
    i = jnp.zeros((1, shape[1]), bool).at[:, 1:-1].set(True)
    return j & i


def fg_predictor_terms(u, v, dt, re, gx, gy, gamma, dx, dy, roll=jnp.roll):
    """Full-array F/G predictor arithmetic (no masking) — the SINGLE home of
    the ~30-term donor-cell formula, shared by the jnp path
    (`compute_fg_interior`) and the fused Pallas step-phase kernel
    (ops/ns2d_fused.py). `roll` abstracts the neighbour gather: jnp.roll on
    the whole array here, jnp.roll on the VMEM window in-kernel — identical
    op sequence, so values agree BITWISE at every cell whose neighbours are
    real (wrap/window-edge cells are masked out by both callers)."""
    idx, idy = 1.0 / dx, 1.0 / dy
    inv_re = 1.0 / re

    uc = u
    ue = roll(u, -1, axis=1)
    uw = roll(u, 1, axis=1)
    un = roll(u, -1, axis=0)
    us = roll(u, 1, axis=0)
    unw = roll(roll(u, -1, axis=0), 1, axis=1)
    vc = v
    ve = roll(v, -1, axis=1)
    vw = roll(v, 1, axis=1)
    vn = roll(v, -1, axis=0)
    vs = roll(v, 1, axis=0)
    vse = roll(roll(v, 1, axis=0), -1, axis=1)

    du2dx = idx * 0.25 * (
        (uc + ue) * (uc + ue) - (uc + uw) * (uc + uw)
    ) + gamma * idx * 0.25 * (
        jnp.abs(uc + ue) * (uc - ue) + jnp.abs(uc + uw) * (uc - uw)
    )
    duvdy = idy * 0.25 * (
        (vc + ve) * (uc + un) - (vs + vse) * (uc + us)
    ) + gamma * idy * 0.25 * (
        jnp.abs(vc + ve) * (uc - un) + jnp.abs(vs + vse) * (uc - us)
    )
    lap_u = idx * idx * (ue - 2.0 * uc + uw) + idy * idy * (un - 2.0 * uc + us)
    f_int = uc + dt * (inv_re * lap_u - du2dx - duvdy + gx)

    duvdx = idx * 0.25 * (
        (uc + un) * (vc + ve) - (uw + unw) * (vc + vw)
    ) + gamma * idx * 0.25 * (
        jnp.abs(uc + un) * (vc - ve) + jnp.abs(uw + unw) * (vc - vw)
    )
    dv2dy = idy * 0.25 * (
        (vc + vn) * (vc + vn) - (vc + vs) * (vc + vs)
    ) + gamma * idy * 0.25 * (
        jnp.abs(vc + vn) * (vc - vn) + jnp.abs(vc + vs) * (vc - vs)
    )
    lap_v = idx * idx * (ve - 2.0 * vc + vw) + idy * idy * (vn - 2.0 * vc + vs)
    g_int = vc + dt * (inv_re * lap_v - duvdx - dv2dy + gy)
    return f_int, g_int


def compute_fg_interior(u, v, dt, re, gx, gy, gamma, dx, dy):
    """Momentum predictor interior only (computeFG, solver.c:360-423): central
    + γ-blended donor-cell convective fluxes, viscous Laplacian, body force.
    Distributed callers gate the wall fixups to wall-owning shards (an ungated
    local fixup would clobber F/G at interior shard edges).

    Full-array formulation: every neighbour is a roll of the whole array
    (wrap values land outside the interior mask), so each output is ONE
    fused elementwise pass — no interior DUS (see _interior_mask). The
    arithmetic lives in `fg_predictor_terms` (shared with the fused kernel)."""
    f_int, g_int = fg_predictor_terms(u, v, dt, re, gx, gy, gamma, dx, dy)
    m = _interior_mask(u.shape)
    f = jnp.where(m, f_int, 0.0)
    g = jnp.where(m, g_int, 0.0)
    return f, g


def apply_fg_wall_fixups(f, g, u, v):
    """Wall fixups: F carries U on vertical walls, G carries V on horizontal
    walls (solver.c:425-435)."""
    f = f.at[1:-1, 0].set(u[1:-1, 0])
    f = f.at[1:-1, -2].set(u[1:-1, -2])
    g = g.at[0, 1:-1].set(v[0, 1:-1])
    g = g.at[-2, 1:-1].set(v[-2, 1:-1])
    return f, g


def rhs_terms(f, g, dt, dx, dy, roll=jnp.roll):
    """Full-array RHS = div(F,G)/dt arithmetic (shared with the fused
    kernel, see fg_predictor_terms for the `roll` contract)."""
    return (1.0 / dt) * (
        (f - roll(f, 1, axis=1)) / dx + (g - roll(g, 1, axis=0)) / dy
    )


def compute_rhs(f, g, dt, dx, dy):
    """Pressure-Poisson RHS = div(F,G)/dt (computeRHS, solver.c:122-138).
    Full-array roll form — one fused pass, no interior DUS
    (_interior_mask)."""
    return jnp.where(_interior_mask(f.shape), rhs_terms(f, g, dt, dx, dy), 0.0)


def adapt_terms(f, g, p, dt, dx, dy, roll=jnp.roll):
    """Full-array projection arithmetic (shared with the fused kernel)."""
    fx = dt / dx
    fy = dt / dy
    u_new = f - (roll(p, -1, axis=1) - p) * fx
    v_new = g - (roll(p, -1, axis=0) - p) * fy
    return u_new, v_new


def adapt_uv(u, v, f, g, p, dt, dx, dy):
    """Projection / velocity correction (adaptUV, solver.c:438-455).
    Full-array roll form — the interior select fuses into the producer
    (_interior_mask); edge cells keep the incoming u/v exactly as the
    at[].set form did."""
    m = _interior_mask(u.shape)
    u_new, v_new = adapt_terms(f, g, p, dt, dx, dy)
    return jnp.where(m, u_new, u), jnp.where(m, v_new, v)


def set_boundary_conditions(u, v, bc_left, bc_right, bc_bottom, bc_top):
    """Wall BCs on ghost/wall strips (setBoundaryConditions, solver.c:236-337).
    bc kinds are static config ⇒ resolved at trace time. PERIODIC is a no-op,
    exactly as in the reference."""
    # left wall: U(0,j) is ON the wall, V(0,j) is a ghost
    if bc_left == NOSLIP:
        u = u.at[1:-1, 0].set(0.0)
        v = v.at[1:-1, 0].set(-v[1:-1, 1])
    elif bc_left == SLIP:
        u = u.at[1:-1, 0].set(0.0)
        v = v.at[1:-1, 0].set(v[1:-1, 1])
    elif bc_left == OUTFLOW:
        u = u.at[1:-1, 0].set(u[1:-1, 1])
        v = v.at[1:-1, 0].set(v[1:-1, 1])
    # right wall: U(imax,j) is on the wall (an interior column!), V(imax+1,j) ghost
    if bc_right == NOSLIP:
        u = u.at[1:-1, -2].set(0.0)
        v = v.at[1:-1, -1].set(-v[1:-1, -2])
    elif bc_right == SLIP:
        u = u.at[1:-1, -2].set(0.0)
        v = v.at[1:-1, -1].set(v[1:-1, -2])
    elif bc_right == OUTFLOW:
        u = u.at[1:-1, -2].set(u[1:-1, -3])
        v = v.at[1:-1, -1].set(v[1:-1, -2])
    # bottom wall: V(i,0) on the wall, U(i,0) ghost
    if bc_bottom == NOSLIP:
        v = v.at[0, 1:-1].set(0.0)
        u = u.at[0, 1:-1].set(-u[1, 1:-1])
    elif bc_bottom == SLIP:
        v = v.at[0, 1:-1].set(0.0)
        u = u.at[0, 1:-1].set(u[1, 1:-1])
    elif bc_bottom == OUTFLOW:
        u = u.at[0, 1:-1].set(u[1, 1:-1])
        v = v.at[0, 1:-1].set(v[1, 1:-1])
    # top wall: V(i,jmax) on the wall, U(i,jmax+1) ghost
    if bc_top == NOSLIP:
        v = v.at[-2, 1:-1].set(0.0)
        u = u.at[-1, 1:-1].set(-u[-2, 1:-1])
    elif bc_top == SLIP:
        v = v.at[-2, 1:-1].set(0.0)
        u = u.at[-1, 1:-1].set(u[-2, 1:-1])
    elif bc_top == OUTFLOW:
        u = u.at[-1, 1:-1].set(u[-2, 1:-1])
        v = v.at[-2, 1:-1].set(v[-3, 1:-1])
    return u, v


def set_special_bc_dcavity(u):
    """Lid U(i,jmax+1) = 2 - U(i,jmax) for i in 1..imax-1 — the reference
    skips the last interior i (solver.c:345-349, a documented quirk we
    replicate for trajectory parity)."""
    return u.at[-1, 1:-2].set(2.0 - u[-2, 1:-2])


def set_special_bc_canal(u, dy, ylength, dtype):
    """Parabolic inflow U(0,j) = y(ylength−y)·4/ylength² (solver.c:350-357)."""
    jmax = u.shape[0] - 2
    y = (jnp.arange(1, jmax + 1, dtype=dtype) - 0.5) * dy
    prof = y * (ylength - y) * 4.0 / (ylength * ylength)
    return u.at[1:-1, 0].set(prof)


def max_element(m):
    """max |m| over the FULL array incl. ghosts — the reference's maxElement
    scans ghost cells too (solver.c:193-202, documented quirk, replicated)."""
    return jnp.max(jnp.abs(m))


def cfl_dt(umax, vmax, dt_bound, dx, dy, tau):
    """CFL scalar math given the velocity maxima — shared by
    compute_timestep and the fused step path (which carries umax/vmax from
    the previous step's fused adapt+max kernel; max is exact regardless of
    reduction order, so the two compositions are bitwise identical)."""
    inf = jnp.asarray(jnp.inf, umax.dtype)
    dt = jnp.minimum(
        dt_bound,
        jnp.minimum(
            jnp.where(umax > 0, dx / umax, inf), jnp.where(vmax > 0, dy / vmax, inf)
        ),
    )
    return dt * tau


def compute_timestep(u, v, dt_bound, dx, dy, tau):
    """Adaptive CFL timestep (computeTimestep, solver.c:219-234)."""
    return cfl_dt(max_element(u), max_element(v), dt_bound, dx, dy, tau)


def normalize_pressure(p):
    """Subtract the mean over the FULL array (normalizePressure, solver.c:204-217)."""
    return p - jnp.mean(p)
