"""Flag-field obstacle cells for NS-3D — the 3-D extension of
ops/obstacle.py (NaSt3D-style boxes), branch-free masks, TPU-first.

The reference has no obstacle support in 2-D or 3-D; the 2-D flag field
implements the BASELINE.json channel-with-obstacle config, and this module
carries the same design to the 3-D solver (assignment-6's model family):

- geometry is static config (.par `obstacles` key: semicolon-separated
  axis-aligned BOXES `x0,y0,z0,x1,y1,z1` in physical coordinates — the 2-D
  form keeps its 4-value rectangles), so all masks are trace-time constants
- velocity: normal components on obstacle faces are zeroed; tangential
  components on faces buried in obstacles mirror the nearest fluid-fluid
  face (priority j± then k± for u, i± then k± for v, i± then j± for w) so
  the interpolated wall velocity is zero (no-slip)
- momentum fluxes: F/G/H carry U/V/W on non-fluid faces (the wall-fixup
  trick, assignment-6/src/solver.c:771-823) so div = 0 across obstacle
  walls and the projection leaves them untouched
- pressure: per-direction fluid coefficients eps_{e,w,n,s,f,b} ∈ {0,1} in
  numerator and denominator — homogeneous Neumann on obstacle surfaces,
  per-cell relaxation ω/denom precomputed; residual and normalization
  reduce over fluid cells only
- the pressure solve dispatches to the flag-masked temporal-blocked 3-D
  Pallas kernel on TPU (ops/sor3d_pallas.py `_tblock3d_kernel(masked=True)`;
  measured 2.7× the jnp eps path at 96³ f32 on v5e — 257 ms → 96 ms,
  the numbers in BASELINE.md/PARITY.md) and to the jnp
  eps-coefficient passes elsewhere; mg/fft are rejected for obstacle runs
  exactly as in 2-D (non-constant-coefficient stencil)

Obstacles must be >= 2 cells thick per axis (validated, like NaSt2D's
flag-consistency check). Layout matches ops/ns3d.py: (kmax+2, jmax+2,
imax+2) arrays [k, j, i]; u on east faces (i), v on north faces (j), w on
back faces (k); the ghost shell counts as fluid so domain-wall BCs compose
unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np


def parse_obstacles_3d(spec: str) -> list[tuple[float, ...]]:
    """Parse `obstacles` as 3-D boxes "x0,y0,z0,x1,y1,z1[;...]"."""
    boxes = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        vals = [float(v) for v in part.split(",")]
        if len(vals) != 6:
            raise ValueError(
                f"3-D obstacle box needs 6 values x0,y0,z0,x1,y1,z1, "
                f"got {part!r}"
            )
        x0, y0, z0, x1, y1, z1 = vals
        boxes.append((
            min(x0, x1), min(y0, y1), min(z0, z1),
            max(x0, x1), max(y0, y1), max(z0, z1),
        ))
    return boxes


def build_fluid_3d(imax, jmax, kmax, dx, dy, dz, spec: str) -> np.ndarray:
    """Boolean fluid mask (kmax+2, jmax+2, imax+2); True = fluid. A cell is
    obstacle iff its center lies inside any box. Ghost shell is always
    fluid (domain walls belong to the wall-BC code)."""
    fluid = np.ones((kmax + 2, jmax + 2, imax + 2), dtype=bool)
    x = (np.arange(imax + 2) - 0.5) * dx
    y = (np.arange(jmax + 2) - 0.5) * dy
    z = (np.arange(kmax + 2) - 0.5) * dz
    for (x0, y0, z0, x1, y1, z1) in parse_obstacles_3d(spec):
        inside = (
            (x[None, None, :] > x0) & (x[None, None, :] < x1)
            & (y[None, :, None] > y0) & (y[None, :, None] < y1)
            & (z[:, None, None] > z0) & (z[:, None, None] < z1)
        )
        fluid &= ~inside
    fluid[0], fluid[-1] = True, True
    fluid[:, 0], fluid[:, -1] = True, True
    fluid[:, :, 0], fluid[:, :, -1] = True, True
    _validate_3d(fluid)
    return fluid


def _validate_3d(fluid: np.ndarray) -> None:
    obs = ~fluid[1:-1, 1:-1, 1:-1]
    thin_i = obs & fluid[1:-1, 1:-1, :-2] & fluid[1:-1, 1:-1, 2:]
    thin_j = obs & fluid[1:-1, :-2, 1:-1] & fluid[1:-1, 2:, 1:-1]
    thin_k = obs & fluid[:-2, 1:-1, 1:-1] & fluid[2:, 1:-1, 1:-1]
    if thin_i.any() or thin_j.any() or thin_k.any():
        raise ValueError(
            "obstacle cells with fluid on two opposite sides (1-cell-thin "
            "walls) are not representable; make obstacles >= 2 cells thick"
        )


@dataclass(frozen=True)
class ObstacleMasks3D:
    """Static mask arrays for one geometry+grid (trace-time constants)."""

    fluid: jnp.ndarray    # (K+2, J+2, I+2) 0/1 cell-is-fluid
    u_face: jnp.ndarray   # 1 where u[k,j,i] is a fluid-fluid face (i dir)
    v_face: jnp.ndarray   # (j dir)
    w_face: jnp.ndarray   # (k dir)
    p_mask: jnp.ndarray   # (K, J, I) interior fluid-cell mask
    eps_e: jnp.ndarray    # (K, J, I): neighbour in +i is fluid (and cell is)
    eps_w: jnp.ndarray
    eps_n: jnp.ndarray    # +j
    eps_s: jnp.ndarray
    eps_b: jnp.ndarray    # +k (back)
    eps_f: jnp.ndarray    # -k (front)
    factor: jnp.ndarray   # (K, J, I) per-cell omega / denom (0 in obstacles)
    n_fluid: float
    omega: float

    @property
    def any_obstacle(self) -> bool:
        full = self.p_mask.shape[0] * self.p_mask.shape[1] * self.p_mask.shape[2]
        return float(self.n_fluid) < full


def make_masks_3d(fluid_np: np.ndarray, dx, dy, dz, omega, dtype
                  ) -> ObstacleMasks3D:
    f = fluid_np
    u_face = f & np.roll(f, -1, axis=2)
    u_face[:, :, -1] = True  # roll wrap on the ghost column; ghosts are fluid
    v_face = f & np.roll(f, -1, axis=1)
    v_face[:, -1, :] = True
    w_face = f & np.roll(f, -1, axis=0)
    w_face[-1, :, :] = True
    fi = f[1:-1, 1:-1, 1:-1]
    eps_e = (f[1:-1, 1:-1, 2:] & fi).astype(np.float64)  # lint: allow(dtype-policy) host-side mask coeffs
    eps_w = (f[1:-1, 1:-1, :-2] & fi).astype(np.float64)  # lint: allow(dtype-policy) host-side mask coeffs
    eps_n = (f[1:-1, 2:, 1:-1] & fi).astype(np.float64)  # lint: allow(dtype-policy) host-side mask coeffs
    eps_s = (f[1:-1, :-2, 1:-1] & fi).astype(np.float64)  # lint: allow(dtype-policy) host-side mask coeffs
    eps_b = (f[2:, 1:-1, 1:-1] & fi).astype(np.float64)  # lint: allow(dtype-policy) host-side mask coeffs
    eps_f = (f[:-2, 1:-1, 1:-1] & fi).astype(np.float64)  # lint: allow(dtype-policy) host-side mask coeffs
    idx2, idy2, idz2 = 1.0 / (dx * dx), 1.0 / (dy * dy), 1.0 / (dz * dz)
    denom = ((eps_e + eps_w) * idx2 + (eps_n + eps_s) * idy2
             + (eps_b + eps_f) * idz2)
    with np.errstate(divide="ignore", invalid="ignore"):
        factor = np.where(denom > 0, omega / denom, 0.0) * fi
    return ObstacleMasks3D(
        fluid=jnp.asarray(f, dtype),
        u_face=jnp.asarray(u_face, dtype),
        v_face=jnp.asarray(v_face, dtype),
        w_face=jnp.asarray(w_face, dtype),
        p_mask=jnp.asarray(fi, dtype),
        eps_e=jnp.asarray(eps_e, dtype),
        eps_w=jnp.asarray(eps_w, dtype),
        eps_n=jnp.asarray(eps_n, dtype),
        eps_s=jnp.asarray(eps_s, dtype),
        eps_b=jnp.asarray(eps_b, dtype),
        eps_f=jnp.asarray(eps_f, dtype),
        factor=jnp.asarray(factor, dtype),
        n_fluid=float(fi.sum()),
        omega=float(omega),
    )


def _mirror(comp, both_obs, faces_and_vals):
    """comp += both_obs * first-hit mirror of the neighbouring fluid-fluid
    faces, in priority order [(face_mask, value), ...]."""
    one = jnp.ones((), comp.dtype)
    acc = jnp.zeros_like(comp)
    remaining = jnp.ones_like(comp)
    for fm, val in faces_and_vals:
        acc = acc + remaining * fm * (-val)
        remaining = remaining * (one - fm)
    return comp + both_obs * acc


def apply_obstacle_velocity_bc_3d(u, v, w, m: ObstacleMasks3D):
    """No-slip on obstacle surfaces: zero normal components on any face
    touching an obstacle; mirror tangential ghosts from the nearest
    fluid-fluid face so interpolated wall velocities vanish (the 3-D form
    of ops/obstacle.apply_obstacle_velocity_bc)."""
    one = jnp.ones((), u.dtype)
    u = u * m.u_face
    v = v * m.v_face
    w = w * m.w_face

    # u-faces buried in obstacles mirror across the nearer tangential wall
    both_u = (one - m.fluid) * (one - jnp.roll(m.fluid, -1, axis=2))
    u = _mirror(u, both_u, [
        (jnp.roll(m.u_face, -1, 1), jnp.roll(u, -1, 1)),   # north (j+1)
        (jnp.roll(m.u_face, 1, 1), jnp.roll(u, 1, 1)),     # south (j-1)
        (jnp.roll(m.u_face, -1, 0), jnp.roll(u, -1, 0)),   # back  (k+1)
        (jnp.roll(m.u_face, 1, 0), jnp.roll(u, 1, 0)),     # front (k-1)
    ])
    both_v = (one - m.fluid) * (one - jnp.roll(m.fluid, -1, axis=1))
    v = _mirror(v, both_v, [
        (jnp.roll(m.v_face, -1, 2), jnp.roll(v, -1, 2)),   # east  (i+1)
        (jnp.roll(m.v_face, 1, 2), jnp.roll(v, 1, 2)),     # west  (i-1)
        (jnp.roll(m.v_face, -1, 0), jnp.roll(v, -1, 0)),   # back
        (jnp.roll(m.v_face, 1, 0), jnp.roll(v, 1, 0)),     # front
    ])
    both_w = (one - m.fluid) * (one - jnp.roll(m.fluid, -1, axis=0))
    w = _mirror(w, both_w, [
        (jnp.roll(m.w_face, -1, 2), jnp.roll(w, -1, 2)),   # east
        (jnp.roll(m.w_face, 1, 2), jnp.roll(w, 1, 2)),     # west
        (jnp.roll(m.w_face, -1, 1), jnp.roll(w, -1, 1)),   # north
        (jnp.roll(m.w_face, 1, 1), jnp.roll(w, 1, 1)),     # south
    ])
    return u, v, w


# -- pressure: eps-coefficient SOR -----------------------------------------

def obstacle_residual_3d(p, rhs, m: ObstacleMasks3D, idx2, idy2, idz2):
    """Interior residual of the 3-D eps-coefficient operator over fluid
    cells — the single home of the obstacle stencil (sor_pass_obstacle_3d
    updates with it; ops/multigrid's 3-D obstacle V-cycle restricts it)."""
    c = p[1:-1, 1:-1, 1:-1]
    lap = (
        m.eps_e * (p[1:-1, 1:-1, 2:] - c) + m.eps_w * (p[1:-1, 1:-1, :-2] - c)
    ) * idx2 + (
        m.eps_n * (p[1:-1, 2:, 1:-1] - c) + m.eps_s * (p[1:-1, :-2, 1:-1] - c)
    ) * idy2 + (
        m.eps_b * (p[2:, 1:-1, 1:-1] - c) + m.eps_f * (p[:-2, 1:-1, 1:-1] - c)
    ) * idz2
    return (rhs[1:-1, 1:-1, 1:-1] - lap) * m.p_mask


def sor_pass_obstacle_3d(p, rhs, color_mask, m: ObstacleMasks3D,
                         idx2, idy2, idz2):
    """One masked half-sweep with per-direction fluid coefficients
    (3-D form of sor_pass_obstacle). Returns (p, sum of masked r²)."""
    r = obstacle_residual_3d(p, rhs, m, idx2, idy2, idz2) * color_mask
    p = p.at[1:-1, 1:-1, 1:-1].add(-m.factor * r)
    return p, jnp.sum(r * r)


def make_obstacle_solver_fn_3d(imax, jmax, kmax, dx, dy, dz, eps, itermax,
                               m: ObstacleMasks3D, dtype,
                               backend: str = "auto", n_inner: int = 1):
    """Pressure-solve convergence loop with 3-D obstacle coefficients.
    Residual normalized by the FLUID cell count (documented deviation from
    the reference's every-cell norm, as in 2-D).

    On TPU with a pallas-capable dtype the loop runs the flag-masked
    temporal-blocked 3-D kernel (ops/sor3d_pallas.py
    `_tblock3d_kernel(masked=True)`, n_inner iterations per HBM sweep —
    same overshoot semantics as the uniform solve); otherwise the jnp
    eps-coefficient passes. Both paths relax with `m.omega`."""
    import jax
    import numpy as np

    from ..models.ns3d import (
        _use_pallas_3d,
        checkerboard_mask_3d,
        neumann_faces_3d,
    )
    from ..utils import flags as _flags

    idx2, idy2, idz2 = 1.0 / (dx * dx), 1.0 / (dy * dy), 1.0 / (dz * dz)
    epssq = eps * eps
    norm = m.n_fluid

    use_pallas = _use_pallas_3d(backend, dtype)
    eff = max(1, n_inner)
    if use_pallas and backend != "pallas":
        from . import sor3d_pallas as sp3

        bk = sp3.pick_block_k(kmax, jmax, imax, dtype, eff, masked=True)
        use_pallas = not sp3.block_k_degenerate(bk, kmax, eff)

    if use_pallas:
        from . import sor3d_pallas as sp3

        rb_iter, block_k = sp3.make_rb_iter_tblock_3d(
            imax, jmax, kmax, dx, dy, dz, m.omega, dtype, n_inner=eff,
            fluid=np.asarray(m.fluid),
        )
        if rb_iter is None:
            raise ValueError("pallas 3-D backend unavailable")
        return sp3.make_tblock_solve_loop(
            rb_iter, block_k, eff, norm, eps, itermax, kmax, jmax, imax, dtype
        )

    odd = checkerboard_mask_3d(kmax, jmax, imax, 1, dtype)
    even = checkerboard_mask_3d(kmax, jmax, imax, 0, dtype)

    def solve(p0, rhs):
        def cond(c):
            _, res, it = c
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(c):
            p, _, it = c
            p, r0 = sor_pass_obstacle_3d(p, rhs, odd, m, idx2, idy2, idz2)
            p, r1 = sor_pass_obstacle_3d(p, rhs, even, m, idx2, idy2, idz2)
            p = neumann_faces_3d(p)
            res = (r0 + r1) / norm
            if _flags.debug():
                jax.debug.print("{} Residuum: {}", it, res)
            return p, res, it + 1

        return jax.lax.while_loop(
            cond, body,
            (p0, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32)),
        )

    return solve


def mask_fgh(f, g, h, u, v, w, m: ObstacleMasks3D):
    """F/G/H carry U/V/W on every non-fluid face — obstacle analog of the
    reference's 6-face wall fixups (solver.c:771-823): the divergence RHS
    sees zero flux across obstacle walls."""
    one = jnp.ones((), f.dtype)
    f = m.u_face * f + (one - m.u_face) * u
    g = m.v_face * g + (one - m.v_face) * v
    h = m.w_face * h + (one - m.w_face) * w
    return f, g, h


def adapt_uvw_obstacle(u, v, w, f, g, h, p, dt, dx, dy, dz,
                       m: ObstacleMasks3D):
    """Projection restricted to fluid-fluid faces (3-D adapt_uv_obstacle)."""
    fx, fy, fz = dt / dx, dt / dy, dt / dz
    I = np.s_[1:-1]
    u_new = f[I, I, I] - (p[I, I, 2:] - p[I, I, I]) * fx
    v_new = g[I, I, I] - (p[I, 2:, I] - p[I, I, I]) * fy
    w_new = h[I, I, I] - (p[2:, I, I] - p[I, I, I]) * fz
    u = u.at[I, I, I].set(u_new * m.u_face[I, I, I])
    v = v.at[I, I, I].set(v_new * m.v_face[I, I, I])
    w = w.at[I, I, I].set(w_new * m.w_face[I, I, I])
    return u, v, w

# ----------------------------------------------------------------------
# Distributed obstacles (call INSIDE shard_map): the geometry is static and
# GLOBAL, so every shard slices its own extended/interior mask blocks from
# the global constants by mesh offsets — no flag exchange, ever (the 3-D
# form of ops/obstacle.shard_masks and friends).
# ----------------------------------------------------------------------


def shard_masks_3d(m: ObstacleMasks3D, kl: int, jl: int, il: int,
                   over_k: int = 0, over_j: int = 0, over_i: int = 0
                   ) -> ObstacleMasks3D:
    """This shard's view of the global mask set: extended-block fields at
    the extended origin, interior fields at the interior origin. `over_*`
    zero-pad the HI sides by the ragged ceil-division overhang so
    trailing-shard slices never clamp (dead cells read zero masks — the
    2-D shard_masks convention)."""
    from jax import lax as _lax

    from ..parallel.comm import get_offsets

    koff = get_offsets("k", kl)
    joff = get_offsets("j", jl)
    ioff = get_offsets("i", il)
    pad = [(0, over_k), (0, over_j), (0, over_i)]

    def ext(a):
        return _lax.dynamic_slice(jnp.pad(a, pad), (koff, joff, ioff),
                                  (kl + 2, jl + 2, il + 2))

    def inter(a):
        return _lax.dynamic_slice(jnp.pad(a, pad), (koff, joff, ioff),
                                  (kl, jl, il))

    return ObstacleMasks3D(
        fluid=ext(m.fluid),
        u_face=ext(m.u_face),
        v_face=ext(m.v_face),
        w_face=ext(m.w_face),
        p_mask=inter(m.p_mask),
        eps_e=inter(m.eps_e),
        eps_w=inter(m.eps_w),
        eps_n=inter(m.eps_n),
        eps_s=inter(m.eps_s),
        eps_b=inter(m.eps_b),
        eps_f=inter(m.eps_f),
        factor=inter(m.factor),
        n_fluid=m.n_fluid,
        omega=m.omega,
    )


def deep_obstacle_masks_3d(m: ObstacleMasks3D, kl, jl, il, halo: int,
                           over_k: int = 0, over_j: int = 0,
                           over_i: int = 0):
    """Interior-mask slices for the deep-halo CA layout (3-D form of
    deep_obstacle_masks): pad the GLOBAL interior constants by H-1 zeros and
    slice at the plain mesh offsets — identical values on every shard that
    sees a cell, so redundant halo updates stay bitwise-consistent.
    `over_*` extend the HI pads by the ragged overhang (deep_pad_widths
    rationale)."""
    from jax import lax as _lax

    from ..parallel.comm import get_offsets

    H = halo
    koff = get_offsets("k", kl)
    joff = get_offsets("j", jl)
    ioff = get_offsets("i", il)
    pad = [(H - 1, H - 1 + over_k), (H - 1, H - 1 + over_j),
           (H - 1, H - 1 + over_i)]
    size = (kl + 2 * H - 2, jl + 2 * H - 2, il + 2 * H - 2)

    def inter(a):
        return _lax.dynamic_slice(jnp.pad(a, pad), (koff, joff, ioff), size)

    return {
        "p_mask": inter(m.p_mask),
        "eps_e": inter(m.eps_e),
        "eps_w": inter(m.eps_w),
        "eps_n": inter(m.eps_n),
        "eps_s": inter(m.eps_s),
        "eps_b": inter(m.eps_b),
        "eps_f": inter(m.eps_f),
        "factor": inter(m.factor),
    }


def _obstacle_half_3d(p, rhs, color, om, idx2, idy2, idz2):
    """One eps-coefficient half-sweep on an extended block — op-for-op
    sor_pass_obstacle_3d for bitwise parity with the single-device path."""
    c = p[1:-1, 1:-1, 1:-1]
    lap = (
        om["eps_e"] * (p[1:-1, 1:-1, 2:] - c)
        + om["eps_w"] * (p[1:-1, 1:-1, :-2] - c)
    ) * idx2 + (
        om["eps_n"] * (p[1:-1, 2:, 1:-1] - c)
        + om["eps_s"] * (p[1:-1, :-2, 1:-1] - c)
    ) * idy2 + (
        om["eps_b"] * (p[2:, 1:-1, 1:-1] - c)
        + om["eps_f"] * (p[:-2, 1:-1, 1:-1] - c)
    ) * idz2
    r = (rhs[1:-1, 1:-1, 1:-1] - lap) * color
    return p.at[1:-1, 1:-1, 1:-1].add(-om["factor"] * r), r


def ca_rb_iters_obstacle_3d(p, rhs, n: int, cm, om, idx2, idy2, idz2):
    """n full red-black iterations of the 3-D eps-coefficient stencil on the
    deep-halo extended block (obstacle twin of stencil3d.ca_rb_iters_3d).
    cm = stencil3d.ca_masks_3d set, om = deep_obstacle_masks_3d set."""
    from ..parallel.stencil3d import neumann_masked_3d

    odd = cm["odd"][1:-1, 1:-1, 1:-1] * om["p_mask"]
    even = cm["even"][1:-1, 1:-1, 1:-1] * om["p_mask"]
    r_odd = r_evn = None
    for _ in range(n):
        p, r_odd = _obstacle_half_3d(p, rhs, odd, om, idx2, idy2, idz2)
        p, r_evn = _obstacle_half_3d(p, rhs, even, om, idx2, idy2, idz2)
        p = neumann_masked_3d(p, cm)
    r2 = jnp.sum(
        jnp.where(
            cm["owned"][1:-1, 1:-1, 1:-1],
            r_odd * r_odd + r_evn * r_evn,
            0.0,
        )
    )
    return p, r2


def make_dist_obstacle_solver_3d(comm, imax, jmax, kmax, kl, jl, il,
                                 dx, dy, dz, eps, itermax,
                                 m: ObstacleMasks3D, dtype, ca_n: int = 1,
                                 sor_inner: int = 1, backend: str = "auto",
                                 ragged: bool = False):
    """Distributed 3-D eps-coefficient pressure solve (shard_map kernel
    side), communication-avoiding like the uniform solve: one depth-2n halo
    exchange buys n exact local red-black iterations (static global masks
    keep redundant halo updates bitwise-consistent). Residual normalized by
    the global fluid-cell count; extent-1 shards fall back to
    exchange-per-half-sweep.

    Returns `(solve, used_pallas)` like the 2-D twin — the dispatch
    decision travels in the return value; the "obstacle3d_dist"
    _dispatch.record is informational only."""
    import jax as _jax

    from ..parallel.comm import halo_exchange, master_print, reduction
    from ..parallel.stencil2d import (
        ca_clamp,
        ca_halo,
        ca_supported,
        embed_deep,
        strip_deep,
    )
    from ..parallel.stencil3d import (
        ca_masks_3d,
        neumann_masked_3d,
    )
    from ..utils import flags as _flags

    idx2, idy2, idz2 = 1.0 / (dx * dx), 1.0 / (dy * dy), 1.0 / (dz * dz)
    epssq = eps * eps
    norm = m.n_fluid
    # ragged CA consumes one extra halo layer (ca_halo): supported from
    # min extent 3
    supported = ca_supported(kl, jl, il) and (
        not ragged or ca_halo(1, True) <= min(kl, jl, il)
    )
    n = ca_clamp(ca_n, kl, jl, il) if supported else 1
    if supported and ragged:
        while n > 1 and ca_halo(n, True) > min(kl, jl, il):
            n -= 1
    # per-shard Pallas kernel dispatch (round 3, mirrors the 2-D
    # make_dist_obstacle_solver): production path on TPU, interpret with
    # backend="pallas" for tests; the jnp CA path keeps ca_n. RAGGED runs
    # stay on the jnp CA path in 3-D: the 3-D padded layout's k-halo is
    # exactly 2n planes (sor3d_pallas.tblock3d_halo), so the ragged 2n+1
    # depth would need the whole padded-k accounting retrofitted — the
    # 2-D kernel has the ragged mode (sor_obsdist ca_halo layout).
    rb_k = None
    if supported and not ragged:
        from ..models.ns3d import _use_pallas_3d

        if backend == "pallas" or _use_pallas_3d("auto", dtype):
            n_k = ca_clamp(max(ca_n, sor_inner), kl, jl, il)
            try:
                from .sor_obsdist3d import make_rb_iters_obsdist_3d

                rb_k, bk_k = make_rb_iters_obsdist_3d(
                    kmax, jmax, imax, kl, jl, il, n_k, dx, dy, dz,
                    m.omega, dtype,
                )
            except ValueError:
                rb_k = None
    from ..utils import dispatch as _dispatch

    if rb_k is not None:
        n = n_k
        _dispatch.record("obstacle3d_dist", f"pallas ca{n}")
    else:
        _dispatch.record(
            "obstacle3d_dist",
            (f"jnp_ca ca{n}" if supported else "jnp_rb_fallback")
            + (" ragged" if ragged else ""),
        )
    H = ca_halo(n, ragged) if supported else 1

    # ragged ceil-division overhang per axis (0 when divisible)
    from ..parallel.stencil2d import ceil_overhang

    over_k = ceil_overhang(comm.axis_size("k"), kl, kmax)
    over_j = ceil_overhang(comm.axis_size("j"), jl, jmax)
    over_i = ceil_overhang(comm.axis_size("i"), il, imax)

    def solve(p, rhs):
        cm = ca_masks_3d(kl, jl, il, H, kmax, jmax, imax, dtype)
        om = deep_obstacle_masks_3d(m, kl, jl, il, H,
                                    over_k=over_k, over_j=over_j,
                                    over_i=over_i)
        pd = embed_deep(p, H)
        rd = halo_exchange(embed_deep(rhs, H), comm, depth=H)
        if rb_k is not None:
            # pad once, carry the padded layout, exchange at padded offsets
            from ..parallel.comm import get_offsets
            from .sor3d_pallas import pad_array_3d, unpad_array_3d
            from .sor_obsdist3d import padded_deep_exchange_3d

            koff = get_offsets("k", kl)
            joff = get_offsets("j", jl)
            ioff = get_offsets("i", il)
            offs = jnp.stack([
                koff.astype(jnp.int32), joff.astype(jnp.int32),
                ioff.astype(jnp.int32),
            ])
            rd_p = pad_array_3d(rd, bk_k, n)
            flg_p = pad_array_3d(
                _jax.lax.dynamic_slice(
                    jnp.pad(m.fluid, [(H - 1, H - 1)] * 3),
                    (koff, joff, ioff),
                    (kl + 2 * H, jl + 2 * H, il + 2 * H),
                ),
                bk_k, n,
            )
            ext = (kl + 2 * H, jl + 2 * H, il + 2 * H)
            h3 = 2 * n  # pad_array_3d's k halo (tblock3d_halo)

            def cond_k(c):
                _, res, it = c
                return jnp.logical_and(res >= epssq, it < itermax)

            def body_k(c):
                pp, _, it = c
                pp = padded_deep_exchange_3d(pp, comm, H, h3, *ext)
                pp, r2 = rb_k(offs, pp, rd_p, flg_p)
                res = reduction(r2, comm, "sum") / norm
                if _flags.debug():
                    master_print(comm, "{} Residuum: {}", it + (n - 1), res)
                return pp, res, it + n

            pp, res, it = _jax.lax.while_loop(
                cond_k, body_k,
                (pad_array_3d(pd, bk_k, n), jnp.asarray(1.0, dtype),
                 jnp.asarray(0, jnp.int32)),
            )
            pd = unpad_array_3d(pp, ext[0] - 2, ext[1] - 2, ext[2] - 2, n)
            return halo_exchange(strip_deep(pd, H), comm), res, it

        def cond(c):
            _, res, it = c
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(c):
            pd, _, it = c
            if supported:
                pd = halo_exchange(pd, comm, depth=H)
                pd, r2 = ca_rb_iters_obstacle_3d(
                    pd, rd, n, cm, om, idx2, idy2, idz2
                )
            else:
                odd = cm["odd"][1:-1, 1:-1, 1:-1] * om["p_mask"]
                even = cm["even"][1:-1, 1:-1, 1:-1] * om["p_mask"]
                pd2 = halo_exchange(pd, comm)
                pd2, r_odd = _obstacle_half_3d(pd2, rd, odd, om,
                                               idx2, idy2, idz2)
                pd2 = halo_exchange(pd2, comm)
                pd2, r_evn = _obstacle_half_3d(pd2, rd, even, om,
                                               idx2, idy2, idz2)
                if ragged:
                    # the wall-ghost plane can open a dead shard whose
                    # Neumann source lives on a neighbour (ca_halo)
                    pd2 = halo_exchange(pd2, comm)
                pd = neumann_masked_3d(pd2, cm)
                r2 = jnp.sum(
                    jnp.where(
                        cm["owned"][1:-1, 1:-1, 1:-1],
                        r_odd * r_odd + r_evn * r_evn,
                        0.0,
                    )
                )
            res = reduction(r2, comm, "sum") / norm
            if _flags.debug():
                master_print(comm, "{} Residuum: {}", it + (n - 1), res)
            return pd, res, it + n

        pd, res, it = _jax.lax.while_loop(
            cond, body, (pd, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32))
        )
        return halo_exchange(strip_deep(pd, H), comm), res, it

    return solve, rb_k is not None
