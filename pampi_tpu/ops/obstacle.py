"""Flag-field obstacle cells for NS-2D — branch-free masks, TPU-first.

The reference has no obstacle support (its canal is an empty channel); this
implements the classic NaSt2D-style flag field (the BASELINE.json
"channel-with-obstacle, flag-masked cells" config) as *precomputed static
masks* instead of per-cell flag branches, so every op stays a fused
whole-array pass:

- geometry is static config (.par `obstacles` key: semicolon-separated
  axis-aligned rectangles in physical coordinates), so all masks are
  trace-time constants
- velocity: normal components on obstacle faces are zeroed; tangential
  components in obstacle boundary cells mirror the adjacent fluid value
  (u_ghost = -u_fluid) so the interpolated wall velocity is zero (no-slip)
- momentum fluxes: F/G carry U/V on obstacle faces (the same trick the
  reference uses at domain walls, solver.c:425-435) so the pressure RHS sees
  div = 0 across obstacle walls and the projection leaves them untouched
- pressure: the SOR stencil uses per-direction fluid coefficients
  (eps_E/W/N/S ∈ {0,1}) in both numerator and denominator — homogeneous
  Neumann dp/dn = 0 on obstacle surfaces, with the cell's relaxation factor
  ω / ((eps_E+eps_W)/dx² + (eps_N+eps_S)/dy²) precomputed as an array; away
  from obstacles it reduces exactly to the uniform formula
- residuals and the pressure normalization reduce over fluid cells only

Obstacles must be at least 2 cells thick in each direction (an obstacle cell
with fluid on two opposite sides has no well-defined mirror value); geometry
violating this is rejected at setup, like NaSt2D's flag-consistency check.

Layout matches ops/ns2d.py: arrays (jmax+2, imax+2), [j, i]; u on east
faces, v on north faces, p at centers; the ghost ring counts as fluid so the
domain-wall BCs (ops/ns2d.py) compose unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np


def parse_obstacles(spec: str) -> list[tuple[float, float, float, float]]:
    """Parse the .par `obstacles` value: "x0,y0,x1,y1[;x0,y0,x1,y1]...".

    Empty/whitespace spec -> no obstacles."""
    rects = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        vals = [float(v) for v in part.split(",")]
        if len(vals) != 4:
            raise ValueError(
                f"obstacle rectangle needs 4 values x0,y0,x1,y1, got {part!r}"
            )
        x0, y0, x1, y1 = vals
        rects.append((min(x0, x1), min(y0, y1), max(x0, x1), max(y0, y1)))
    return rects


def build_fluid(imax: int, jmax: int, dx: float, dy: float, spec: str):
    """Boolean fluid mask (jmax+2, imax+2); True = fluid. A cell is obstacle
    iff its center lies inside any rectangle. Ghost ring is always fluid
    (domain walls are handled by the wall-BC code, not the flag field)."""
    fluid = np.ones((jmax + 2, imax + 2), dtype=bool)
    x = (np.arange(imax + 2) - 0.5) * dx  # center of cell column i
    y = (np.arange(jmax + 2) - 0.5) * dy
    for (x0, y0, x1, y1) in parse_obstacles(spec):
        inside = (
            (x[None, :] > x0) & (x[None, :] < x1)
            & (y[:, None] > y0) & (y[:, None] < y1)
        )
        fluid &= ~inside
    fluid[0, :] = fluid[-1, :] = True
    fluid[:, 0] = fluid[:, -1] = True
    _validate(fluid)
    return fluid


def _validate(fluid: np.ndarray) -> None:
    obs = ~fluid[1:-1, 1:-1]
    thin_h = obs & fluid[1:-1, :-2] & fluid[1:-1, 2:]
    thin_v = obs & fluid[:-2, 1:-1] & fluid[2:, 1:-1]
    if thin_h.any() or thin_v.any():
        raise ValueError(
            "obstacle cells with fluid on two opposite sides (1-cell-thin "
            "walls) are not representable; make obstacles >= 2 cells thick"
        )


@dataclass(frozen=True)
class ObstacleMasks:
    """Static mask arrays for one geometry+grid (trace-time constants)."""

    fluid: jnp.ndarray       # (J+2, I+2) 0/1 cell-is-fluid
    u_face: jnp.ndarray      # (J+2, I+2) 1 where u[j,i] is a fluid-fluid face
    v_face: jnp.ndarray      # (J+2, I+2) 1 where v[j,i] is a fluid-fluid face
    p_mask: jnp.ndarray      # (J, I) interior fluid-cell mask for residuals
    eps_e: jnp.ndarray       # (J, I) interior: east neighbour is fluid
    eps_w: jnp.ndarray
    eps_n: jnp.ndarray
    eps_s: jnp.ndarray
    factor: jnp.ndarray      # (J, I) per-cell omega / denom (0 in obstacles)
    n_fluid: float           # number of interior fluid cells
    omega: float             # the relaxation factor baked into `factor`

    @property
    def any_obstacle(self) -> bool:
        return float(self.n_fluid) < (self.p_mask.shape[0] * self.p_mask.shape[1])


def make_masks(fluid_np: np.ndarray, dx: float, dy: float, omega: float,
               dtype) -> ObstacleMasks:
    f = fluid_np
    u_face = f & np.roll(f, -1, axis=1)
    u_face[:, -1] = True  # roll wrap on the ghost column; ghosts are fluid
    v_face = f & np.roll(f, -1, axis=0)
    v_face[-1, :] = True
    fi = f[1:-1, 1:-1]
    eps_e = (f[1:-1, 2:] & fi).astype(np.float64)  # lint: allow(dtype-policy) host-side mask coeffs
    eps_w = (f[1:-1, :-2] & fi).astype(np.float64)  # lint: allow(dtype-policy) host-side mask coeffs
    eps_n = (f[2:, 1:-1] & fi).astype(np.float64)  # lint: allow(dtype-policy) host-side mask coeffs
    eps_s = (f[:-2, 1:-1] & fi).astype(np.float64)  # lint: allow(dtype-policy) host-side mask coeffs
    idx2, idy2 = 1.0 / (dx * dx), 1.0 / (dy * dy)
    denom = (eps_e + eps_w) * idx2 + (eps_n + eps_s) * idy2
    with np.errstate(divide="ignore", invalid="ignore"):
        factor = np.where(denom > 0, omega / denom, 0.0) * fi
    return ObstacleMasks(
        fluid=jnp.asarray(f, dtype),
        u_face=jnp.asarray(u_face, dtype),
        v_face=jnp.asarray(v_face, dtype),
        p_mask=jnp.asarray(fi, dtype),
        eps_e=jnp.asarray(eps_e, dtype),
        eps_w=jnp.asarray(eps_w, dtype),
        eps_n=jnp.asarray(eps_n, dtype),
        eps_s=jnp.asarray(eps_s, dtype),
        factor=jnp.asarray(factor, dtype),
        n_fluid=float(fi.sum()),
        omega=float(omega),
    )


def apply_obstacle_velocity_bc(u, v, m: ObstacleMasks):
    """No-slip on obstacle surfaces.

    1) Normal components: u/v on any face touching an obstacle cell are
       zeroed (the face mask).
    2) Tangential ghosts: a u-face BETWEEN two obstacle cells that sits one
       row below/above a fluid-fluid face mirrors it (u = -u_fluid), so the
       velocity interpolated at the horizontal obstacle wall is zero — the
       same -u ghost trick the domain-wall NOSLIP case uses
       (ops/ns2d.py set_boundary_conditions). v symmetric with columns.
       Faces deeper inside an obstacle stay 0.
    """
    one = jnp.ones((), u.dtype)
    u = u * m.u_face
    v = v * m.v_face
    # u: faces with both cells obstacle; mirror across the nearer horizontal wall
    both_obs_u = (one - m.fluid) * (one - jnp.roll(m.fluid, -1, axis=1))
    uf_n = jnp.roll(m.u_face, -1, axis=0)  # fluid-fluid face one row north
    uf_s = jnp.roll(m.u_face, 1, axis=0)
    u_n = jnp.roll(u, -1, axis=0)
    u_s = jnp.roll(u, 1, axis=0)
    u = u + both_obs_u * (uf_n * (-u_n) + (one - uf_n) * uf_s * (-u_s))
    # v: faces with both cells obstacle; mirror across the nearer vertical wall
    both_obs_v = (one - m.fluid) * (one - jnp.roll(m.fluid, -1, axis=0))
    vf_e = jnp.roll(m.v_face, -1, axis=1)
    vf_w = jnp.roll(m.v_face, 1, axis=1)
    v_e = jnp.roll(v, -1, axis=1)
    v_w = jnp.roll(v, 1, axis=1)
    v = v + both_obs_v * (vf_e * (-v_e) + (one - vf_e) * vf_w * (-v_w))
    return u, v


# -- pressure: eps-coefficient SOR -----------------------------------------

def obstacle_residual(p, rhs, m: ObstacleMasks, idx2, idy2):
    """Interior residual of the eps-coefficient operator over fluid cells —
    the single home of the obstacle stencil (sor_pass_obstacle updates with
    it; ops/multigrid's obstacle V-cycle restricts it)."""
    c = p[1:-1, 1:-1]
    lap = (
        m.eps_e * (p[1:-1, 2:] - c) + m.eps_w * (p[1:-1, :-2] - c)
    ) * idx2 + (
        m.eps_n * (p[2:, 1:-1] - c) + m.eps_s * (p[:-2, 1:-1] - c)
    ) * idy2
    return (rhs[1:-1, 1:-1] - lap) * m.p_mask


def sor_pass_obstacle(p, rhs, color_mask, m: ObstacleMasks, idx2, idy2):
    """One masked half-sweep with per-direction fluid coefficients.

    r = rhs - [eps_e(pE - c) + eps_w(pW - c)]/dx² - [eps_n(pN - c) + eps_s(pS - c)]/dy²
    p -= (omega/denom) * r      (denom per cell, precomputed in m.factor;
                                 note m.factor already includes omega)
    restricted to `color_mask` ∩ fluid. Returns (p, sum of masked r²)."""
    r = obstacle_residual(p, rhs, m, idx2, idy2) * color_mask
    p = p.at[1:-1, 1:-1].add(-m.factor * r)
    return p, jnp.sum(r * r)


def make_obstacle_solver_fn(imax, jmax, dx, dy, eps, itermax, m: ObstacleMasks,
                            dtype, backend: str = "auto", n_inner: int = 1):
    """Full pressure-solve convergence loop with obstacle coefficients:
    (p0, rhs) -> (p, res, it) as one jittable `lax.while_loop` — the obstacle
    counterpart of models/poisson.make_solver_fn. The residual is normalized
    by the number of FLUID cells (the reference's imax·jmax norm counts every
    interior cell; obstacle cells carry no residual — documented deviation).

    On TPU with a pallas-capable dtype the loop runs the flag-masked
    temporal-blocked kernel (ops/sor_pallas.py `_tblock_kernel(masked=True)`,
    n_inner iterations per HBM sweep — same overshoot semantics as
    make_solver_fn); otherwise the jnp eps-coefficient passes. Both paths
    relax with `m.omega` — the ω the masks were built with — so backends
    cannot drift apart."""
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax, dtype,
                    f"sor_obstacle {imax}x{jmax}")
    import jax

    from ..models.poisson import _use_pallas
    from .sor import checkerboard_mask, neumann_bc

    idx2, idy2 = 1.0 / (dx * dx), 1.0 / (dy * dy)
    epssq = eps * eps
    norm = m.n_fluid

    if _use_pallas(backend, dtype):
        from . import sor_pallas as sp

        rb_iter, block_rows, halo = sp.make_rb_iter_tblock(
            imax, jmax, dx, dy, m.omega, dtype, n_inner=max(1, n_inner),
            fluid=np.asarray(m.fluid),
        )
        if rb_iter is None:
            raise ValueError("pallas backend unavailable")
        eff = max(1, n_inner)

        def step(p_pad, rhs_pad):
            p_pad, rsq = rb_iter(p_pad, rhs_pad)
            return p_pad, rsq / norm

        def prep(x):
            return sp.pad_array(x, block_rows, halo)

        def post(x):
            return sp.unpad_array(x, jmax, imax, halo)
    else:
        red = checkerboard_mask(jmax, imax, 0, dtype)
        black = checkerboard_mask(jmax, imax, 1, dtype)
        eff = 1

        def step(p, rhs):
            p, r0 = sor_pass_obstacle(p, rhs, red, m, idx2, idy2)
            p, r1 = sor_pass_obstacle(p, rhs, black, m, idx2, idy2)
            return neumann_bc(p), (r0 + r1) / norm

        prep = post = lambda x: x  # noqa: E731

    def solve(p0, rhs):
        rhs = prep(rhs)

        def cond(carry):
            _, res, it = carry
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(carry):
            p, _, it = carry
            p, res = step(p, rhs)
            return p, res, it + eff

        init = (prep(p0), jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32))
        p, res, it = jax.lax.while_loop(cond, body, init)
        return post(p), res, it

    return solve


def normalize_pressure_fluid(p, m: ObstacleMasks):
    """Subtract the mean over fluid cells (interior+ghosts counted as in the
    reference's full-array mean, but obstacle cells excluded — their p is
    meaningless)."""
    total = jnp.sum(p * m.fluid)
    count = jnp.sum(m.fluid)
    return p - total / count


def mask_fg(f, g, u, v, m: ObstacleMasks):
    """F carries U (and G carries V) on every non-fluid face — obstacle
    analog of the reference's wall fixups (solver.c:425-435): the divergence
    RHS then sees zero flux across obstacle walls and adaptUV leaves their
    face velocities untouched."""
    one = jnp.ones((), f.dtype)
    f = m.u_face * f + (one - m.u_face) * u
    g = m.v_face * g + (one - m.v_face) * v
    return f, g


def adapt_uv_obstacle(u, v, f, g, p, dt, dx, dy, m: ObstacleMasks):
    """Projection restricted to fluid-fluid faces (with mask_fg applied the
    unmasked projection is already a no-op on obstacle faces; the explicit
    mask keeps them exactly zero against float drift)."""
    fx = dt / dx
    fy = dt / dy
    u_new = f[1:-1, 1:-1] - (p[1:-1, 2:] - p[1:-1, 1:-1]) * fx
    v_new = g[1:-1, 1:-1] - (p[2:, 1:-1] - p[1:-1, 1:-1]) * fy
    u = u.at[1:-1, 1:-1].set(u_new * m.u_face[1:-1, 1:-1])
    v = v.at[1:-1, 1:-1].set(v_new * m.v_face[1:-1, 1:-1])
    return u, v


# ----------------------------------------------------------------------
# Distributed obstacles (call INSIDE shard_map): the geometry is static and
# GLOBAL, so every shard slices its own extended/interior mask blocks from
# the global constants by mesh offsets — no flag exchange, ever.
# ----------------------------------------------------------------------


def shard_masks(m: ObstacleMasks, jl: int, il: int,
                over_j: int = 0, over_i: int = 0) -> ObstacleMasks:
    """This shard's view of the global mask set: extended-block fields
    (fluid/u_face/v_face) sliced at the extended origin, interior fields at
    the interior origin. The sliced blocks agree across neighbouring shards
    wherever they overlap (same global constants), which is what keeps the
    distributed obstacle arithmetic bitwise-consistent. `over_j`/`over_i`
    zero-pad the HI sides by the ragged ceil-division overhang so
    trailing-shard slices never clamp (dead cells read zero masks: no
    updates, no faces, no residual)."""
    from jax import lax as _lax

    from ..parallel.comm import get_offsets

    joff = get_offsets("j", jl)
    ioff = get_offsets("i", il)
    pad = [(0, over_j), (0, over_i)]

    def ext(a):
        return _lax.dynamic_slice(jnp.pad(a, pad), (joff, ioff),
                                  (jl + 2, il + 2))

    def inter(a):
        return _lax.dynamic_slice(jnp.pad(a, pad), (joff, ioff), (jl, il))

    return ObstacleMasks(
        fluid=ext(m.fluid),
        u_face=ext(m.u_face),
        v_face=ext(m.v_face),
        p_mask=inter(m.p_mask),
        eps_e=inter(m.eps_e),
        eps_w=inter(m.eps_w),
        eps_n=inter(m.eps_n),
        eps_s=inter(m.eps_s),
        factor=inter(m.factor),
        n_fluid=m.n_fluid,
        omega=m.omega,
    )


def make_dist_obstacle_solver(comm, imax, jmax, jl, il, dx, dy, eps, itermax,
                              m: ObstacleMasks, dtype, ca_n: int = 1,
                              sor_inner: int = 1, backend: str = "auto",
                              ragged: bool = False,
                              record_key: str = "obstacle_dist"):
    """Distributed eps-coefficient pressure solve (shard_map kernel side),
    COMMUNICATION-AVOIDING like the uniform solve: one depth-2n halo
    exchange buys n exact red-black iterations computed locally (the static
    global masks make redundant halo updates bitwise-consistent). Same
    per-cell arithmetic as the single-device jnp path (sor_pass_obstacle);
    with ca_n > 1 convergence is checked every n iterations, so a solve may
    overshoot by up to n-1 iterations vs the per-iteration single-device
    loop (the tpu_ca_inner contract) — at n=1 trajectories match exactly.
    Residual normalized by the global fluid-cell count. Extent-1 shards
    fall back to exchange-per-half-sweep.

    On TPU (or backend="pallas": interpret off-TPU, the test mode) the loop
    dispatches the per-shard flag-masked Pallas kernel (ops/sor_obsdist.py)
    at depth max(ca_n, sor_inner); the jnp CA path keeps ca_n so its
    trajectory granularity is unchanged.

    Returns `(solve, used_pallas)` — callers that need the dispatch
    decision (e.g. to relax shard_map's check_vma around the pallas_call)
    read it from the return value; the "obstacle_dist" _dispatch.record is
    informational only (driver artifacts, tests).

    `ragged=True` (round 5, VERDICT r4 item 2): the grid is ceil-divided
    with trailing dead cells — the same per-shard kernel runs with halo
    depth 2n+1 (stencil2d.ca_halo's ragged layer) and overhang-safe global
    constant padding (deep_pad_widths); dead cells carry zero flags, so the
    global-coordinate gating already excludes them from updates, walls and
    residuals. The reference's remainder ranks run the identical optimized
    solver (assignment-6/src/comm.c:19-22 sizeOfRank) — this is that
    property for the flag-masked kernel."""
    from ..utils.precision import check_eps_floor

    check_eps_floor(eps, imax * jmax, dtype,
                    f"sor_dist_obstacle {imax}x{jmax}")
    from ..parallel.comm import (
        get_offsets,
        halo_exchange,
        master_print,
        reduction,
    )
    from ..parallel.stencil2d import (
        ca_clamp,
        ca_halo,
        ca_masks,
        ca_supported,
        embed_deep,
        neumann_masked,
        strip_deep,
    )
    from ..utils import dispatch as _dispatch
    from ..utils import flags as _flags

    idx2, idy2 = 1.0 / (dx * dx), 1.0 / (dy * dy)
    epssq = eps * eps
    norm = m.n_fluid
    # ragged CA consumes one extra halo layer (ca_halo), so the deep
    # strips fit the owned extents only from min extent 3
    supported = ca_supported(jl, il) and (
        not ragged or ca_halo(1, True) <= min(jl, il)
    )

    # per-shard Pallas kernel dispatch (round 3): production path on TPU
    rb_k = None
    if supported:
        from ..models.poisson import _use_pallas

        if backend == "pallas" or _use_pallas("auto", dtype):
            from .sor_obsdist import make_rb_iters_obsdist

            # back off the depth on VMEM infeasibility (deep n inflates
            # the kernel's unrolled-sweep stack): a shallower pallas
            # kernel beats the jnp fallback at any depth
            n_k = ca_clamp(max(ca_n, sor_inner), jl, il)
            while ragged and n_k > 1 and ca_halo(n_k, True) > min(jl, il):
                n_k -= 1
            while n_k >= 1:
                try:
                    # interpret resolves off the backend inside the maker
                    # (real kernel on TPU, interpret elsewhere — the test
                    # mode)
                    rb_k, br_k, h_k = make_rb_iters_obsdist(
                        jmax, imax, jl, il, n_k, dx, dy, m.omega, dtype,
                        ragged=ragged,
                    )
                    break
                except ValueError:
                    rb_k = None
                    n_k //= 2
    if rb_k is not None:
        n = n_k
        _dispatch.record(
            record_key, f"pallas ca{n}" + (" ragged" if ragged else "")
        )
    else:
        n = ca_clamp(ca_n, jl, il) if supported else 1
        if supported and ragged:
            while n > 1 and ca_halo(n, True) > min(jl, il):
                n -= 1
        _dispatch.record(
            record_key,
            (f"jnp_ca ca{n}" if supported else "jnp_rb_fallback")
            + (" ragged" if ragged else ""),
        )
    H = ca_halo(n, ragged) if supported else 1

    # ragged ceil-division overhang per axis (0 when divisible): global
    # constants pad their HI side by it so trailing-shard slices never
    # clamp (stencil2d.deep_pad_widths)
    from ..parallel.stencil2d import deep_pad_widths

    pw_j = deep_pad_widths(H, jl, comm.axis_size("j"), jmax)
    pw_i = deep_pad_widths(H, il, comm.axis_size("i"), imax)

    def solve(p, rhs):
        cm = ca_masks(jl, il, H, jmax, imax, dtype)
        om = deep_obstacle_masks(m, jl, il, H, over_j=pw_j[1] - pw_j[0],
                                 over_i=pw_i[1] - pw_i[0])
        pd = embed_deep(p, H)
        rd = halo_exchange(embed_deep(rhs, H), comm, depth=H)
        if rb_k is not None:
            # pallas path: pad ONCE, carry the padded layout through the
            # loop, exchange at the padded offsets (sor_obsdist.
            # padded_deep_exchange) — pad/unpad per body iteration was the
            # dominant envelope cost at small shard sizes
            from . import sor_pallas as sp
            from .sor_obsdist import padded_deep_exchange

            joff = get_offsets("j", jl)
            ioff = get_offsets("i", il)
            offs = jnp.stack(
                [joff.astype(jnp.int32), ioff.astype(jnp.int32)]
            )
            rd_p = sp.pad_array(rd, br_k, h_k)
            # the deep fluid block: global flags padded by H-1 dead cells
            # (hi side absorbs the ragged overhang), shard slice at the
            # plain mesh offsets (deep_obstacle_masks convention, full
            # extended block)
            import jax as _jx

            flg_p = sp.pad_array(
                _jx.lax.dynamic_slice(
                    jnp.pad(m.fluid, [pw_j, pw_i]),
                    (joff, ioff), (jl + 2 * H, il + 2 * H),
                ),
                br_k, h_k,
            )
            ext_j, ext_i = jl + 2 * H, il + 2 * H

            def cond_k(c):
                _, res, it = c
                return jnp.logical_and(res >= epssq, it < itermax)

            def body_k(c):
                pp, _, it = c
                pp = padded_deep_exchange(pp, comm, H, h_k, ext_j, ext_i)
                pp, r2 = rb_k(offs, pp, rd_p, flg_p)
                res = reduction(r2, comm, "sum") / norm
                if _flags.debug():
                    master_print(
                        comm, "{} Residuum: {}", it + (n - 1), res
                    )
                return pp, res, it + n

            import jax as _jax2

            pp, res, it = _jax2.lax.while_loop(
                cond_k, body_k,
                (sp.pad_array(pd, br_k, h_k), jnp.asarray(1.0, dtype),
                 jnp.asarray(0, jnp.int32)),
            )
            pd = sp.unpad_array(pp, ext_j - 2, ext_i - 2, h_k)
            return halo_exchange(strip_deep(pd, H), comm), res, it

        def cond(c):
            _, res, it = c
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(c):
            pd, _, it = c
            if supported:
                pd = halo_exchange(pd, comm, depth=H)
                pd, r2 = ca_rb_iters_obstacle(pd, rd, n, cm, om, idx2, idy2)
            else:
                red = cm["red"][1:-1, 1:-1] * om["p_mask"]
                black = cm["black"][1:-1, 1:-1] * om["p_mask"]
                pd2 = halo_exchange(pd, comm)
                pd2, r_red = _obstacle_half(pd2, rd, red, om, idx2, idy2)
                pd2 = halo_exchange(pd2, comm)
                pd2, r_blk = _obstacle_half(pd2, rd, black, om, idx2, idy2)
                if ragged:
                    # the wall-ghost row can open a dead shard whose
                    # Neumann source lives on a neighbour (ca_halo)
                    pd2 = halo_exchange(pd2, comm)
                pd = neumann_masked(pd2, cm)
                r2 = jnp.sum(
                    jnp.where(
                        cm["owned"][1:-1, 1:-1],
                        r_red * r_red + r_blk * r_blk,
                        0.0,
                    )
                )
            res = reduction(r2, comm, "sum") / norm
            if _flags.debug():
                master_print(comm, "{} Residuum: {}", it + (n - 1), res)
            return pd, res, it + n

        import jax as _jax

        pd, res, it = _jax.lax.while_loop(
            cond, body, (pd, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32))
        )
        return halo_exchange(strip_deep(pd, H), comm), res, it

    return solve, rb_k is not None


def deep_obstacle_masks(m: ObstacleMasks, jl: int, il: int, halo: int,
                        over_j: int = 0, over_i: int = 0):
    """Interior-mask slices for the deep-halo CA layout (stencil2d.ca_*):
    the update region of a (jl+2H, il+2H) block is its [1:-1] interior, and
    its cell (a, b) sits at global interior index (a - (H-1) + joff, …) —
    so pad the GLOBAL interior mask constants by H-1 (zeros: out-of-domain
    cells update nothing and carry no residual) and slice at the plain mesh
    offsets. `over_j`/`over_i` extend the HI-side pad by the ragged
    ceil-division overhang so trailing-shard slices never clamp
    (stencil2d.deep_pad_widths rationale). Static geometry ⇒ identical
    values on every shard that sees a cell ⇒ redundant halo updates stay
    bitwise-consistent."""
    from jax import lax as _lax

    from ..parallel.comm import get_offsets

    H = halo
    joff = get_offsets("j", jl)
    ioff = get_offsets("i", il)
    pad = [(H - 1, H - 1 + over_j), (H - 1, H - 1 + over_i)]
    size = (jl + 2 * H - 2, il + 2 * H - 2)

    def inter(a):
        return _lax.dynamic_slice(jnp.pad(a, pad), (joff, ioff), size)

    return {
        "p_mask": inter(m.p_mask),
        "eps_e": inter(m.eps_e),
        "eps_w": inter(m.eps_w),
        "eps_n": inter(m.eps_n),
        "eps_s": inter(m.eps_s),
        "factor": inter(m.factor),
    }


def _obstacle_half(p, rhs, color, om, idx2, idy2):
    """One eps-coefficient half-sweep on an extended block — the SINGLE home
    of the distributed obstacle stencil arithmetic (op-for-op
    sor_pass_obstacle for bitwise parity with the single-device jnp path).
    `color` is the precomputed (colour ∩ global interior ∩ fluid) mask on
    the block's [1:-1] region."""
    c = p[1:-1, 1:-1]
    lap = (
        om["eps_e"] * (p[1:-1, 2:] - c) + om["eps_w"] * (p[1:-1, :-2] - c)
    ) * idx2 + (
        om["eps_n"] * (p[2:, 1:-1] - c) + om["eps_s"] * (p[:-2, 1:-1] - c)
    ) * idy2
    r = (rhs[1:-1, 1:-1] - lap) * color
    return p.at[1:-1, 1:-1].add(-om["factor"] * r), r


def ca_rb_iters_obstacle(p, rhs, n: int, cm, om, idx2, idy2):
    """n full red-black iterations of the eps-coefficient obstacle stencil
    on the deep-halo extended block (the obstacle twin of
    stencil2d.ca_rb_iters). cm = stencil2d.ca_masks set, om =
    deep_obstacle_masks set. Returns (p, owned r² sum)."""
    from ..parallel.stencil2d import neumann_masked

    red = cm["red"][1:-1, 1:-1] * om["p_mask"]
    black = cm["black"][1:-1, 1:-1] * om["p_mask"]
    r_red = r_blk = None
    for _ in range(n):
        p, r_red = _obstacle_half(p, rhs, red, om, idx2, idy2)
        p, r_blk = _obstacle_half(p, rhs, black, om, idx2, idy2)
        p = neumann_masked(p, cm)
    r2 = jnp.sum(
        jnp.where(cm["owned"][1:-1, 1:-1], r_red * r_red + r_blk * r_blk, 0.0)
    )
    return p, r2
