"""Fused NS-3D step-phase Pallas kernels — the 3-D twin of ops/ns2d_fused.py.

Same motivation and equivalence policy as the 2-D module (launch-latency
amortization of the non-solve phase chain; copies/selects/maxes bitwise,
compound F/G/H / RHS / projection arithmetic ulp-equivalent via the SHARED
formula functions ops/ns3d.fgh_predictor_terms / rhs_terms_3d /
adapt_terms_3d with a roll-based window shift):

  PRE  (u, v, w, dt)  -> (u', v', w', F, G, H, rhs)
       6-face wall BCs -> special BC -> F/G/H predictor + wall fixups ->
       Poisson RHS
  POST (u', v', w', F, G, H, p, dt)
       -> (u'', v'', w'', max|u''|, max|v''|, max|w''|)
       projection adaptUV + the 3-D CFL max reduction

Layout: blocks along k (the untiled outermost axis — halo planes need no
alignment rounding), full padded (jp, ip) planes per k-slice
(sor3d_pallas.padded_ji tiling). `pad3`/`unpad3` convert at the chunk/step
boundary. All writes are gated by GLOBAL coordinates (offsets via scalar
prefetch), so the same kernels serve the single-device solver (offsets 0)
and the distributed twin (per-shard deep-halo blocks, depth FUSE_DEEP_HALO
exchange per step).

Obstacle flag fields compose branch-free exactly like the 2-D module: the
padded 0/1 fluid flag rides as a fourth input window and
u_face/v_face/w_face are derived in-kernel (integer-exact parity with
ops/obstacle3d.make_masks_3d including the ghost-plane wrap fixes), so the
3-D obstacle velocity BC (priority-ordered tangential mirrors), the F/G/H
face masks and the projection face masks are the same flag-multiply forms
the jnp path uses. Single-device callers bake the flag as a padded
constant (`fluid=<array>`); distributed callers pass `fluid=True` and
feed the per-shard global-constant slice at call time. Ragged shards are
the same kernels at uneven block bounds (global gating), with
POST(ragged=True) appending the live-mask multiply of the jnp ragged
chain (parallel/ragged3d.live_masks_3d).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import ns3d as ops3
from .ns2d_fused import (  # shared validity chain + overlap rim
    FUSE_CHAIN,
    FUSE_DEEP_HALO,
    FUSE_FOOTPRINT,
    OVERLAP_RIM,
)
from .sor_pallas import (
    LANE,
    VMEM_LIMIT_BYTES,
    CompilerParams,
    _align,
    _check_dtype,
    pltpu,
)

NOSLIP, SLIP, OUTFLOW, PERIODIC = 1, 2, 3, 4

__all__ = [
    "FUSE_CHAIN", "FUSE_DEEP_HALO", "FUSE_FOOTPRINT", "OVERLAP_RIM",
    "make_fused_pre_3d", "make_fused_post_3d", "make_fused_step_3d",
    "probe_fused_3d",
]


def _win_shift(a, dk=0, dj=0, di=0):
    """fgh_predictor_terms' `sh` contract on the VMEM window: roll so that
    out[x] = a[x + (dk, dj, di)] (identical neighbour values at every cell
    whose neighbours are real)."""
    out = a
    if dk:
        out = jnp.roll(out, -dk, axis=0)
    if dj:
        out = jnp.roll(out, -dj, axis=1)
    if di:
        out = jnp.roll(out, -di, axis=2)
    return out


def apply_wall_bcs_3d(u, v, w, gk, gj, gi, bcs, gkmax, gjmax, gimax):
    """set_boundary_conditions_3d as sequential global-coordinate-gated
    where-updates: same face order (the bcs dict's insertion order = the
    reference's application order), same written values. axes: 0=k, 1=j,
    2=i; normal component per axis {0: w, 1: v, 2: u}."""
    fields = {0: w, 1: v, 2: u}
    coords = {0: gk, 1: gj, 2: gi}
    gmaxes = {0: gkmax, 1: gjmax, 2: gimax}
    tans = {
        a: (coords[a] >= 1) & (coords[a] <= gmaxes[a]) for a in (0, 1, 2)
    }
    from .ns3d import FACES

    for face, kind in bcs.items():
        axis, side = FACES[face]
        g = coords[axis]
        t_axes = [a for a in (0, 1, 2) if a != axis]
        tan = tans[t_axes[0]] & tans[t_axes[1]]
        if side == "lo":
            ghost = (g == 0) & tan
            wall = (g == 0) & tan
            wall_in = -1  # read one plane inward: roll(x, -1, axis)
        else:
            ghost = (g == gmaxes[axis] + 1) & tan
            wall = (g == gmaxes[axis]) & tan
            wall_in = 1
        normal = fields[axis]
        zero = jnp.zeros((), normal.dtype)

        def inward(x, s=wall_in, a=axis):
            return jnp.roll(x, s, axis=a)

        if kind == NOSLIP:
            fields[axis] = jnp.where(wall, zero, normal)
            for a in t_axes:
                fields[a] = jnp.where(ghost, -inward(fields[a]), fields[a])
        elif kind == SLIP:
            fields[axis] = jnp.where(wall, zero, normal)
            for a in t_axes:
                fields[a] = jnp.where(ghost, inward(fields[a]), fields[a])
        elif kind == OUTFLOW:
            fields[axis] = jnp.where(wall, inward(normal), normal)
            for a in t_axes:
                fields[a] = jnp.where(ghost, inward(fields[a]), fields[a])
        elif kind == PERIODIC:
            pass
    return fields[2], fields[1], fields[0]


def apply_special_bc_3d(u, gk, gj, gi, problem, gkmax, gjmax, gimax):
    """set_special_bc_dcavity_3d / set_special_bc_canal_3d in gated-where
    form (incl. the reference's skip-last-interior-i-AND-k lid quirk)."""
    if problem == "dcavity":
        m = (
            (gj == gjmax + 1)
            & (gk >= 1) & (gk <= gkmax - 1)
            & (gi >= 1) & (gi <= gimax - 1)
        )
        u = jnp.where(m, 2.0 - jnp.roll(u, 1, axis=1), u)
    elif problem == "canal":
        m = (
            (gi == 0)
            & (gk >= 1) & (gk <= gkmax)
            & (gj >= 1) & (gj <= gjmax)
        )
        u = jnp.where(m, jnp.full((), 2.0, u.dtype), u)
    return u


def _obstacle_faces_3d(fl, gk, gj, gi, gkmax, gjmax, gimax, sh=_win_shift):
    """u/v/w_face derived from the 0/1 fluid flag window — integer-exact
    parity with ops/obstacle3d.make_masks_3d (incl. its ghost-plane
    wrap-fixes: the last global ghost column/row/plane is forced to a
    face). `sh` is the window's neighbour-shift contract."""
    one = jnp.ones((), fl.dtype)
    u_face = jnp.where(gi == gimax + 1, one, fl * sh(fl, 0, 0, 1))
    v_face = jnp.where(gj == gjmax + 1, one, fl * sh(fl, 0, 1, 0))
    w_face = jnp.where(gk == gkmax + 1, one, fl * sh(fl, 1, 0, 0))
    return u_face, v_face, w_face


def apply_obstacle_velocity_bc_3d_window(u, v, w, fl, u_face, v_face,
                                         w_face, sh=_win_shift):
    """ops/obstacle3d.apply_obstacle_velocity_bc_3d transcribed on the
    window: zero normal components on faces touching an obstacle, then the
    priority-ordered first-hit tangential mirror (`_mirror`) with `sh` as
    the neighbour read. Every wrapped read the full-array form relies on is
    multiplied by zero at the cells that could see window wrap (the ghost
    shell is always fluid), as in the 2-D transcription."""
    one = jnp.ones((), u.dtype)
    u = u * u_face
    v = v * v_face
    w = w * w_face

    def mirror(comp, both_obs, faces_and_vals):
        acc = jnp.zeros_like(comp)
        remaining = jnp.ones_like(comp)
        for fm, val in faces_and_vals:
            acc = acc + remaining * fm * (-val)
            remaining = remaining * (one - fm)
        return comp + both_obs * acc

    both_u = (one - fl) * (one - sh(fl, 0, 0, 1))
    u = mirror(u, both_u, [
        (sh(u_face, 0, 1, 0), sh(u, 0, 1, 0)),     # north (j+1)
        (sh(u_face, 0, -1, 0), sh(u, 0, -1, 0)),   # south (j-1)
        (sh(u_face, 1, 0, 0), sh(u, 1, 0, 0)),     # back  (k+1)
        (sh(u_face, -1, 0, 0), sh(u, -1, 0, 0)),   # front (k-1)
    ])
    both_v = (one - fl) * (one - sh(fl, 0, 1, 0))
    v = mirror(v, both_v, [
        (sh(v_face, 0, 0, 1), sh(v, 0, 0, 1)),     # east  (i+1)
        (sh(v_face, 0, 0, -1), sh(v, 0, 0, -1)),   # west  (i-1)
        (sh(v_face, 1, 0, 0), sh(v, 1, 0, 0)),     # back
        (sh(v_face, -1, 0, 0), sh(v, -1, 0, 0)),   # front
    ])
    both_w = (one - fl) * (one - sh(fl, 1, 0, 0))
    w = mirror(w, both_w, [
        (sh(w_face, 0, 0, 1), sh(w, 0, 0, 1)),     # east
        (sh(w_face, 0, 0, -1), sh(w, 0, 0, -1)),   # west
        (sh(w_face, 0, 1, 0), sh(w, 0, 1, 0)),     # north
        (sh(w_face, 0, -1, 0), sh(w, 0, -1, 0)),   # south
    ])
    return u, v, w


def _pre3_kernel(
    sref,    # SMEM scalar prefetch: int32[3] = (koff, joff, ioff)
    dt_ref,  # SMEM (1, 1)
    *refs,   # [u, v, w(, flg)] + [u', v', w', f, g, h, rhs] + scratch
    block_k: int,
    nblocks: int,
    gkmax: int,
    gjmax: int,
    gimax: int,
    lkmax: int,
    ljmax: int,
    limax: int,
    ext_pad: int,
    halo: int,
    bcs: tuple,      # tuple of (face, kind) — dict order preserved
    problem: str | None,
    re: float,
    gx: float,
    gy: float,
    gz: float,
    gamma: float,
    dx: float,
    dy: float,
    dz: float,
    masked: bool,
    bands: tuple | None = None,
    dynamic: bool = False,
):
    if dynamic:
        # shape-class mode (the 2-D _pre_kernel contract): live extents
        # and per-lane cell sizes as SMEM scalars after dt
        ext_ref, geo_ref, *refs = refs
    if masked:
        (u_in, v_in, w_in, flg, u_out, v_out, w_out, f_out, g_out, h_out,
         r_out, uw2, vw2, ww2, fw2, ob2, ld_sem, st_sem) = refs
    else:
        (u_in, v_in, w_in, u_out, v_out, w_out, f_out, g_out, h_out, r_out,
         uw2, vw2, ww2, ob2, ld_sem, st_sem) = refs
        flg = fw2 = None
    b = pl.program_id(0)
    bk = block_k
    h = halo
    slot = b % 2
    nslot = (b + 1) % 2
    koff = sref[0]
    joff = sref[1]
    ioff = sref[2]
    dt = dt_ref[0, 0]
    if dynamic:
        # single-device class lanes: local extents == global extents
        gkmax = ext_ref[0, 0]
        gjmax = ext_ref[0, 1]
        gimax = ext_ref[0, 2]
        lkmax, ljmax, limax = gkmax, gjmax, gimax
        dx = geo_ref[0, 0]
        dy = geo_ref[0, 1]
        dz = geo_ref[0, 2]

    # banded (grid-restricted) sweeps over the leading k axis — the 3-D
    # twin of the ns2d_fused band mapping (`tpu_overlap_restrict`); the
    # full-sweep default keeps the literal k*bk indexing (byte-identical
    # historical trace)
    if bands is None or (len(bands) == 1 and bands[0][0] == 0):
        def plane_of(k):
            return k * bk
    else:
        def plane_of(k):
            row, acc = None, 0
            for s, n in bands:
                r = s + (k - acc) * bk
                row = r if row is None else jnp.where(k >= acc, r, row)
                acc += n
            return row

    def load(k, s):
        r0 = plane_of(k)
        ins = [(u_in, uw2), (v_in, vw2), (w_in, ww2)]
        if masked:
            ins.append((flg, fw2))
        return [
            pltpu.make_async_copy(
                arr.at[pl.ds(r0, bk + 2 * h)], win.at[s],
                ld_sem.at[s, q])
            for q, (arr, win) in enumerate(ins)
        ]

    def store(k, s):
        r0 = plane_of(k)
        outs = (u_out, v_out, w_out, f_out, g_out, h_out, r_out)
        return [
            pltpu.make_async_copy(
                ob2.at[s, q], outs[q].at[pl.ds(h + r0, bk)],
                st_sem.at[s, q])
            for q in range(7)
        ]

    @pl.when(b == 0)
    def _():
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    u = uw2[slot]
    v = vw2[slot]
    w = ww2[slot]

    # window cell (wk, wj, wi): deep-block index a_k = plane_of(b)+wk-h,
    # global extended index gk = a_k - ext_pad + koff (and j/i likewise)
    a_k = plane_of(b) - h + jax.lax.broadcasted_iota(jnp.int32, u.shape, 0)
    a_j = jax.lax.broadcasted_iota(jnp.int32, u.shape, 1)
    a_i = jax.lax.broadcasted_iota(jnp.int32, u.shape, 2)
    gk = a_k - ext_pad + koff
    gj = a_j - ext_pad + joff
    gi = a_i - ext_pad + ioff

    # dead-cell-zero invariant on the loaded windows (ns2d_fused rationale:
    # the carried padded arrays' unstored halo/tail planes are undefined)
    ext_k = lkmax + 2 + 2 * ext_pad
    ext_j = ljmax + 2 + 2 * ext_pad
    ext_i = limax + 2 + 2 * ext_pad
    live_in = (
        (a_k >= 0) & (a_k < ext_k)
        & (a_j >= 0) & (a_j < ext_j)
        & (a_i >= 0) & (a_i < ext_i)
    )
    u = jnp.where(live_in, u, 0.0)
    v = jnp.where(live_in, v, 0.0)
    w = jnp.where(live_in, w, 0.0)

    u, v, w = apply_wall_bcs_3d(
        u, v, w, gk, gj, gi, dict(bcs), gkmax, gjmax, gimax
    )
    u = apply_special_bc_3d(u, gk, gj, gi, problem, gkmax, gjmax, gimax)
    if masked:
        fl = fw2[slot]
        u_face, v_face, w_face = _obstacle_faces_3d(
            fl, gk, gj, gi, gkmax, gjmax, gimax
        )
        u, v, w = apply_obstacle_velocity_bc_3d_window(
            u, v, w, fl, u_face, v_face, w_face
        )

    f_full, g_full, h_full = ops3.fgh_predictor_terms(
        u, v, w, dt, re, gx, gy, gz, gamma, dx, dy, dz, sh=_win_shift
    )
    interior = (
        (gk >= 1) & (gk <= gkmax)
        & (gj >= 1) & (gj <= gjmax)
        & (gi >= 1) & (gi <= gimax)
    )
    tan_k = (gk >= 1) & (gk <= gkmax)
    tan_j = (gj >= 1) & (gj <= gjmax)
    tan_i = (gi >= 1) & (gi <= gimax)
    f = jnp.where(interior, f_full, 0.0)
    g = jnp.where(interior, g_full, 0.0)
    hh = jnp.where(interior, h_full, 0.0)
    # wall fixups (apply_fgh_wall_fixups): F=U on left/right, G=V on
    # bottom/top, H=W on front/back walls
    f = jnp.where(((gi == 0) | (gi == gimax)) & tan_k & tan_j, u, f)
    g = jnp.where(((gj == 0) | (gj == gjmax)) & tan_k & tan_i, v, g)
    hh = jnp.where(((gk == 0) | (gk == gkmax)) & tan_j & tan_i, w, hh)
    if masked:
        # F/G/H carry U/V/W on non-fluid faces (obstacle3d.mask_fgh)
        one = jnp.ones((), u.dtype)
        f = u_face * f + (one - u_face) * u
        g = v_face * g + (one - v_face) * v
        hh = w_face * hh + (one - w_face) * w

    local_int = (
        (a_k >= ext_pad + 1) & (a_k <= ext_pad + lkmax)
        & (a_j >= ext_pad + 1) & (a_j <= ext_pad + ljmax)
        & (a_i >= ext_pad + 1) & (a_i <= ext_pad + limax)
    )
    rhs = jnp.where(
        interior & local_int,
        ops3.rhs_terms_3d(f, g, hh, dt, dx, dy, dz, sh=_win_shift),
        0.0,
    )

    @pl.when(b >= 2)
    def _():
        for c in store(b - 2, slot):
            c.wait()

    for q, arr in enumerate((u, v, w, f, g, hh, rhs)):
        ob2[slot, q] = arr[h: h + bk]
    for c in store(b, slot):
        c.start()

    @pl.when(b == nblocks - 1)
    def _():
        for c in store(b, slot):
            c.wait()
        if nblocks > 1:
            for c in store(b - 1, nslot):
                c.wait()


def _post3_kernel(
    sref,    # SMEM scalar prefetch: int32[3]
    dt_ref,  # SMEM (1, 1)
    *refs,   # [u, v, w, f, g, h, p(, flg)] + [u', v', w', umax, vmax, wmax] + scratch
    block_k: int,
    nblocks: int,
    gkmax: int,
    gjmax: int,
    gimax: int,
    ext_pad: int,
    halo: int,
    dx: float,
    dy: float,
    dz: float,
    masked: bool,
    ragged: bool,
    dynamic: bool = False,
):
    if dynamic:
        ext_ref, geo_ref, *refs = refs
    if masked:
        (ub, vb, wb, fb, gb, hb, p_in, flg,
         u_out, v_out, w_out, umax, vmax, wmax,
         bw2, pw2, fw2, ob2, macc, ld_sem, st_sem) = refs
    else:
        (ub, vb, wb, fb, gb, hb, p_in,
         u_out, v_out, w_out, umax, vmax, wmax,
         bw2, pw2, ob2, macc, ld_sem, st_sem) = refs
        flg = fw2 = None
    b = pl.program_id(0)
    bk = block_k
    h = halo
    slot = b % 2
    nslot = (b + 1) % 2
    koff = sref[0]
    joff = sref[1]
    ioff = sref[2]
    dt = dt_ref[0, 0]
    if dynamic:
        gkmax = ext_ref[0, 0]
        gjmax = ext_ref[0, 1]
        gimax = ext_ref[0, 2]
        dx = geo_ref[0, 0]
        dy = geo_ref[0, 1]
        dz = geo_ref[0, 2]

    def load(k, s):
        copies = [
            pltpu.make_async_copy(
                arr.at[pl.ds(h + k * bk, bk)], bw2.at[s, q],
                ld_sem.at[s, q])
            for q, arr in enumerate((ub, vb, wb, fb, gb, hb))
        ]
        copies.append(pltpu.make_async_copy(
            p_in.at[pl.ds(k * bk, bk + 2 * h)], pw2.at[s], ld_sem.at[s, 6]))
        if masked:
            copies.append(pltpu.make_async_copy(
                flg.at[pl.ds(k * bk, bk + 2 * h)], fw2.at[s],
                ld_sem.at[s, 7]))
        return copies

    def store(k, s):
        return [
            pltpu.make_async_copy(
                ob2.at[s, q], arr.at[pl.ds(h + k * bk, bk)],
                st_sem.at[s, q])
            for q, arr in enumerate((u_out, v_out, w_out))
        ]

    @pl.when(b == 0)
    def _():
        macc[...] = jnp.zeros_like(macc)
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    u = bw2[slot, 0]
    v = bw2[slot, 1]
    w = bw2[slot, 2]
    f = bw2[slot, 3]
    g = bw2[slot, 4]
    hh = bw2[slot, 5]
    pw = pw2[slot]
    pc = pw[h: h + bk]

    def sh_p(x, dk=0, dj=0, di=0):
        # adapt_terms_3d's shift contract on the p window: +1 in k comes
        # from the halo plane above the owned band, in-plane shifts roll
        if dk:
            return pw[h + dk: h + bk + dk]
        return _win_shift(x, 0, dj, di)

    a_k = b * bk + jax.lax.broadcasted_iota(jnp.int32, u.shape, 0)
    a_j = jax.lax.broadcasted_iota(jnp.int32, u.shape, 1)
    a_i = jax.lax.broadcasted_iota(jnp.int32, u.shape, 2)
    gk = a_k - ext_pad + koff
    gj = a_j - ext_pad + joff
    gi = a_i - ext_pad + ioff
    interior = (
        (gk >= 1) & (gk <= gkmax)
        & (gj >= 1) & (gj <= gjmax)
        & (gi >= 1) & (gi <= gimax)
    )

    ua, va, wa = ops3.adapt_terms_3d(f, g, hh, pc, dt, dx, dy, dz, sh=sh_p)
    if masked:
        # projection restricted to fluid-fluid faces (adapt_uvw_obstacle):
        # faces derived from the flag window, the +k shift served from the
        # halo plane above the owned band (the sh_p contract)
        flw = fw2[slot]
        flc = flw[h: h + bk]

        def sh_f(x, dk=0, dj=0, di=0):
            if dk:
                return flw[h + dk: h + bk + dk]
            return _win_shift(x, 0, dj, di)

        u_face, v_face, w_face = _obstacle_faces_3d(
            flc, gk, gj, gi, gkmax, gjmax, gimax, sh=sh_f
        )
        ua = ua * u_face
        va = va * v_face
        wa = wa * w_face
    u = jnp.where(interior, ua, u)
    v = jnp.where(interior, va, v)
    w = jnp.where(interior, wa, w)
    if ragged:
        # the jnp ragged chain's live-mask multiply (ragged3d.live_masks_3d)
        # op-for-op: dead pad cells go to zero after the projection so the
        # ghost-inclusive CFL scan never sees garbage
        live = ((gk <= gkmax + 1) & (gj <= gjmax + 1)
                & (gi <= gimax + 1)).astype(u.dtype)
        u = u * live
        v = v * live
        w = w * live

    @pl.when(b >= 2)
    def _():
        for c in store(b - 2, slot):
            c.wait()

    ob2[slot, 0] = u
    ob2[slot, 1] = v
    ob2[slot, 2] = w
    for c in store(b, slot):
        c.start()

    # ghost-inclusive 3-D maxElement (solver.c:299-310), dead cells and
    # stale deep halos excluded
    valid = (
        (gk >= 0) & (gk <= gkmax + 1)
        & (gj >= 0) & (gj <= gjmax + 1)
        & (gi >= 0) & (gi <= gimax + 1)
    )
    zero = jnp.zeros((), u.dtype)
    for q, arr in enumerate((u, v, w)):
        m = jnp.max(jnp.where(valid, jnp.abs(arr), zero), axis=(0, 1))
        macc[q: q + 1, :] = jnp.maximum(macc[q: q + 1, :], m[None, :])

    @pl.when(b == nblocks - 1)
    def _():
        umax[0, 0] = jnp.max(macc[0:1, :])
        vmax[0, 0] = jnp.max(macc[1:2, :])
        wmax[0, 0] = jnp.max(macc[2:3, :])
        for c in store(b, slot):
            c.wait()
        if nblocks > 1:
            for c in store(b - 1, nslot):
                c.wait()


def fused3_vmem_bytes(bk: int, h: int, jp: int, ip: int, itemsize: int,
                      masked: bool = False) -> int:
    """Scratch bytes of the larger kernel (pre: 3-4 windows + 7 out bands;
    post: 6 in bands + 1-2 windows + 3 out bands), double buffered, plus
    the per-lane max accumulator."""
    plane = jp * ip
    win = (bk + 2 * h) * plane
    band = bk * plane
    pre = 2 * ((4 if masked else 3) * win + 7 * band)
    post = 2 * (6 * band + (2 if masked else 1) * win + 3 * band) + 3 * ip
    return itemsize * max(pre, post)


def pick_block_k_fused(kext: int, jp: int, ip: int, dtype,
                       masked: bool = False) -> int:
    """Block depth: budget the resident planes (20·bk + 12·h of the pre
    kernel, +2·bk+4·h for the flag window) against half the raised VMEM
    limit, capped by the whole grid."""
    plane = jp * ip * jnp.dtype(dtype).itemsize
    h = FUSE_CHAIN
    per_bk = 22 if masked else 20
    per_h = 16 if masked else 12
    feasible = ((VMEM_LIMIT_BYTES // 2) // plane - per_h * h) // per_bk
    return max(1, min(feasible, kext, 32))


def fused_deep_layout_3d(kl: int, jl: int, il: int, dtype, ext_pad: int,
                         block_k: int | None = None,
                         masked: bool = False):
    """(block_k, halo, plane_width, nblocks) of the distributed 3-D
    deep-halo padded layout — the geometry `parallel/overlap.region_plan`
    bands over (the 3-D twin of ns2d_fused.fused_deep_layout_2d; the
    plan's `width` is the padded j*i plane)."""
    ext_k = kl + 2 + 2 * ext_pad
    ext_j = jl + 2 + 2 * ext_pad
    ext_i = il + 2 + 2 * ext_pad
    a = _align(dtype)
    jp = -(-ext_j // a) * a
    ip = -(-ext_i // LANE) * LANE
    if block_k is None:
        block_k = pick_block_k_fused(ext_k, jp, ip, dtype, masked)
    nblocks = -(-ext_k // block_k)
    return block_k, FUSE_CHAIN, jp * ip, nblocks


def _geom3(gkmax, gjmax, gimax, dtype, kl, jl, il, ext_pad, fluid, block_k,
           interpret):
    """Shared geometry/feasibility resolution (the 2-D _geom contract):
    `fluid` is None (no obstacles), a global (kmax+2, jmax+2, imax+2) 0/1
    array (single-device: baked in as a padded constant), or True
    (distributed: the per-shard flag block is an extra call-time arg)."""
    if pltpu is None:
        raise ValueError("pallas TPU backend unavailable")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)
    lkmax = gkmax if kl is None else kl
    ljmax = gjmax if jl is None else jl
    limax = gimax if il is None else il
    ext_k = lkmax + 2 + 2 * ext_pad
    ext_j = ljmax + 2 + 2 * ext_pad
    ext_i = limax + 2 + 2 * ext_pad
    a = _align(dtype)
    jp = -(-ext_j // a) * a
    ip = -(-ext_i // LANE) * LANE
    h = FUSE_CHAIN
    masked = fluid is not None
    if block_k is None:
        block_k = pick_block_k_fused(ext_k, jp, ip, dtype, masked)
    nblocks = -(-ext_k // block_k)
    kp = nblocks * block_k + 2 * h
    itemsize = jnp.dtype(dtype).itemsize
    if fused3_vmem_bytes(block_k, h, jp, ip, itemsize, masked) > VMEM_LIMIT_BYTES // 2:
        raise ValueError(
            f"fused 3-D step-phase scratch {fused3_vmem_bytes(block_k, h, jp, ip, itemsize, masked) >> 20} MiB "
            f"exceeds the VMEM budget (block_k={block_k}, plane {jp}x{ip}); "
            "the jnp phase chain is the fallback"
        )

    def pad3(x):
        out = jnp.zeros((kp, jp, ip), x.dtype)
        return out.at[h: h + x.shape[0], : x.shape[1], : x.shape[2]].set(x)

    def unpad3(xp):
        return xp[h: h + ext_k, :ext_j, :ext_i]

    flg_padded = None
    if masked and fluid is not True:
        import numpy as np

        flg_padded = pad3(jnp.asarray(np.asarray(fluid), dtype))
    return (interpret, lkmax, ljmax, limax, h, block_k, jp, ip, nblocks,
            kp, masked, pad3, unpad3, flg_padded)


def make_fused_pre_3d(
    param,
    gkmax: int,
    gjmax: int,
    gimax: int,
    dx: float,
    dy: float,
    dz: float,
    dtype,
    *,
    kl: int | None = None,
    jl: int | None = None,
    il: int | None = None,
    ext_pad: int = 0,
    fluid=None,
    block_k: int | None = None,
    interpret: bool | None = None,
    grid_bands: tuple | None = None,
    dynamic: bool = False,
):
    """Build the 3-D PRE kernel:
      pre(offs_i32[3], dt_11, u_pad, v_pad, w_pad)
          -> (u', v', w', f, g, h, rhs)                            [padded]
    plus (pad3, unpad3, halo). Geometry contract as make_fused_pre_2d;
    fluid=True (distributed obstacles) appends a call-time flag argument
    (the padded per-shard deep-halo slice of the global flag).
    `grid_bands` restricts the Pallas grid to k-plane bands of the same
    padded layout (see make_fused_pre_2d — the grid-restricted overlap
    halves). `dynamic=True` (the 3-D shape-class chunk): extents/cell
    sizes as call-time SMEM scalars — the call becomes
    pre(offs, ext_i32_13, geo_13, dt11, u, v, w) with ext =
    (kmax, jmax, imax) and geo = (dx, dy, dz); single-device only."""
    if dynamic and (fluid is not None or grid_bands is not None):
        raise ValueError(
            "dynamic extents are the single-device shape-class mode "
            "(no obstacle flags, no grid bands)")
    (interpret, lkmax, ljmax, limax, h, block_k, jp, ip, nblocks, kp,
     masked, pad3, unpad3, flg_padded) = _geom3(
        gkmax, gjmax, gimax, dtype, kl, jl, il, ext_pad, fluid, block_k,
        interpret)
    bcs = (
        ("top", param.bcTop), ("bottom", param.bcBottom),
        ("left", param.bcLeft), ("right", param.bcRight),
        ("front", param.bcFront), ("back", param.bcBack),
    )
    if grid_bands is not None:
        from ..parallel.overlap import check_bands

        check_bands(grid_bands, block_k, nblocks, label="block_k")
        nblocks = sum(n for _, n in grid_bands)
    kernel = functools.partial(
        _pre3_kernel,
        bands=grid_bands,
        block_k=block_k,
        nblocks=nblocks,
        gkmax=gkmax,
        gjmax=gjmax,
        gimax=gimax,
        lkmax=lkmax,
        ljmax=ljmax,
        limax=limax,
        ext_pad=ext_pad,
        halo=h,
        bcs=bcs,
        problem=param.name.replace("3d", ""),
        re=param.re,
        gx=param.gx,
        gy=param.gy,
        gz=param.gz,
        gamma=param.gamma,
        dx=dx,
        dy=dy,
        dz=dz,
        masked=masked,
        dynamic=dynamic,
    )
    n_in = 4 if masked else 3
    pre_scratch = [
        pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype),
        pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype),
        pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype),
    ]
    if masked:
        pre_scratch.append(pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype))
    pre_scratch += [
        pltpu.VMEM((2, 7, block_k, jp, ip), dtype),
        pltpu.SemaphoreType.DMA((2, n_in)),
        pltpu.SemaphoreType.DMA((2, 7)),
    ]
    call = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nblocks,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
            * (3 if dynamic else 1)
            + [pl.BlockSpec(memory_space=pl.ANY)] * n_in,
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 7,
            scratch_shapes=pre_scratch,
        ),
        out_shape=[jax.ShapeDtypeStruct((kp, jp, ip), dtype)] * 7,
        compiler_params=CompilerParams(vmem_limit_bytes=VMEM_LIMIT_BYTES),
        interpret=interpret,
    )

    if dynamic:

        def pre(offs, ext, geo, dt11, u_pad, v_pad, w_pad):
            return call(offs, dt11, ext, geo, u_pad, v_pad, w_pad)
    elif masked and flg_padded is None:

        def pre(offs, dt11, u_pad, v_pad, w_pad, flg_pad):
            return call(offs, dt11, u_pad, v_pad, w_pad, flg_pad)
    elif masked:

        def pre(offs, dt11, u_pad, v_pad, w_pad):
            return call(offs, dt11, u_pad, v_pad, w_pad, flg_padded)
    else:

        def pre(offs, dt11, u_pad, v_pad, w_pad):
            return call(offs, dt11, u_pad, v_pad, w_pad)

    return pre, pad3, unpad3, h


def make_fused_post_3d(
    param,
    gkmax: int,
    gjmax: int,
    gimax: int,
    dx: float,
    dy: float,
    dz: float,
    dtype,
    *,
    kl: int | None = None,
    jl: int | None = None,
    il: int | None = None,
    ext_pad: int = 0,
    fluid=None,
    ragged: bool = False,
    block_k: int | None = None,
    interpret: bool | None = None,
    dynamic: bool = False,
):
    """Build the 3-D POST kernel:
      post(offs_i32[3], dt_11, u, v, w, f, g, h, p)  [all padded]
          -> (u'', v'', w'', umax, vmax, wmax).
    fluid=True appends a call-time flag argument (the padded per-shard
    EXTENDED-block slice of the global flag); ragged=True appends the
    dead-cell live-mask multiply after the projection. `dynamic=True`
    as in make_fused_pre_3d: post(offs, ext, geo, dt11, u, v, w, f, g,
    h, p) with extent-gated masks."""
    if dynamic and fluid is not None:
        raise ValueError(
            "dynamic extents are the single-device shape-class mode "
            "(no obstacle flags)")
    (interpret, lkmax, ljmax, limax, h, block_k, jp, ip, nblocks, kp,
     masked, pad3, unpad3, flg_padded) = _geom3(
        gkmax, gjmax, gimax, dtype, kl, jl, il, ext_pad, fluid, block_k,
        interpret)
    del lkmax, ljmax, limax
    kernel = functools.partial(
        _post3_kernel,
        block_k=block_k,
        nblocks=nblocks,
        gkmax=gkmax,
        gjmax=gjmax,
        gimax=gimax,
        ext_pad=ext_pad,
        halo=h,
        dx=dx,
        dy=dy,
        dz=dz,
        masked=masked,
        ragged=ragged,
        dynamic=dynamic,
    )
    n_in_post = 8 if masked else 7
    post_scratch = [
        pltpu.VMEM((2, 6, block_k, jp, ip), dtype),
        pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype),
    ]
    if masked:
        post_scratch.append(pltpu.VMEM((2, block_k + 2 * h, jp, ip), dtype))
    post_scratch += [
        pltpu.VMEM((2, 3, block_k, jp, ip), dtype),
        pltpu.VMEM((3, ip), dtype),
        pltpu.SemaphoreType.DMA((2, n_in_post)),
        pltpu.SemaphoreType.DMA((2, 3)),
    ]
    call = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nblocks,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
            * (3 if dynamic else 1)
            + [pl.BlockSpec(memory_space=pl.ANY)] * n_in_post,
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3
            + [pl.BlockSpec(memory_space=pltpu.SMEM)] * 3,
            scratch_shapes=post_scratch,
        ),
        out_shape=[jax.ShapeDtypeStruct((kp, jp, ip), dtype)] * 3
        + [jax.ShapeDtypeStruct((1, 1), dtype)] * 3,
        compiler_params=CompilerParams(vmem_limit_bytes=VMEM_LIMIT_BYTES),
        interpret=interpret,
    )

    if dynamic:

        def post(offs, ext, geo, dt11, u_pad, v_pad, w_pad, f_pad, g_pad,
                 h_pad, p_pad):
            u_pad, v_pad, w_pad, um, vm, wm = call(
                offs, dt11, ext, geo, u_pad, v_pad, w_pad, f_pad, g_pad,
                h_pad, p_pad
            )
            return u_pad, v_pad, w_pad, um[0, 0], vm[0, 0], wm[0, 0]
    elif masked and flg_padded is None:

        def post(offs, dt11, u_pad, v_pad, w_pad, f_pad, g_pad, h_pad,
                 p_pad, flg_pad):
            u_pad, v_pad, w_pad, um, vm, wm = call(
                offs, dt11, u_pad, v_pad, w_pad, f_pad, g_pad, h_pad,
                p_pad, flg_pad
            )
            return u_pad, v_pad, w_pad, um[0, 0], vm[0, 0], wm[0, 0]
    elif masked:

        def post(offs, dt11, u_pad, v_pad, w_pad, f_pad, g_pad, h_pad,
                 p_pad):
            u_pad, v_pad, w_pad, um, vm, wm = call(
                offs, dt11, u_pad, v_pad, w_pad, f_pad, g_pad, h_pad,
                p_pad, flg_padded
            )
            return u_pad, v_pad, w_pad, um[0, 0], vm[0, 0], wm[0, 0]
    else:

        def post(offs, dt11, u_pad, v_pad, w_pad, f_pad, g_pad, h_pad,
                 p_pad):
            u_pad, v_pad, w_pad, um, vm, wm = call(
                offs, dt11, u_pad, v_pad, w_pad, f_pad, g_pad, h_pad, p_pad
            )
            return u_pad, v_pad, w_pad, um[0, 0], vm[0, 0], wm[0, 0]

    return post, pad3, unpad3, h


def make_fused_step_3d(
    param,
    gkmax: int,
    gjmax: int,
    gimax: int,
    dx: float,
    dy: float,
    dz: float,
    dtype,
    *,
    fluid=None,
    block_k: int | None = None,
    interpret: bool | None = None,
):
    """The single-device composition (pre + post on the whole grid).
    Returns (pre, post, pad3, unpad3, halo). `fluid` switches on the
    obstacle mode with the global flag baked in as a padded constant."""
    pre, pad3, unpad3, h = make_fused_pre_3d(
        param, gkmax, gjmax, gimax, dx, dy, dz, dtype, fluid=fluid,
        block_k=block_k, interpret=interpret,
    )
    post, _p, _u, _h = make_fused_post_3d(
        param, gkmax, gjmax, gimax, dx, dy, dz, dtype, fluid=fluid,
        block_k=block_k, interpret=interpret,
    )
    return pre, post, pad3, unpad3, h


_PROBE_OK: bool | None = None


def probe_fused_3d() -> bool:
    """One-time smoke test of the 3-D fused pair on the real backend."""
    global _PROBE_OK
    if _PROBE_OK is None:
        try:
            from ..utils.params import Parameter

            param = Parameter(name="dcavity3d", imax=30, jmax=30, kmax=30)
            pre, post, pad3, _unpad3, _h = make_fused_step_3d(
                param, 30, 30, 30, 1.0 / 30, 1.0 / 30, 1.0 / 30,
                jnp.float32, interpret=False,
            )
            z = pad3(jnp.zeros((32, 32, 32), jnp.float32))
            offs = jnp.zeros((3,), jnp.int32)
            dt11 = jnp.full((1, 1), 0.01, jnp.float32)
            up, vp, wp, fp, gp, hp, _r = pre(offs, dt11, z, z, z)
            out = post(offs, dt11, up, vp, wp, fp, gp, hp, z)
            float(out[3])  # force completion
            _PROBE_OK = True
        except Exception:  # lint: allow(broad-except) — probe contract: any failure means "don't dispatch"
            import warnings

            warnings.warn(
                "fused 3-D NS step-phase kernels unavailable; keeping the "
                "jnp phase chain",
                stacklevel=2,
            )
            _PROBE_OK = False
    return _PROBE_OK
