"""Fused multigrid V-cycle: the whole restrict→smooth→prolong chain of
ops/multigrid.py as two Pallas launches per cycle (PR 16, ROADMAP item 1).

The historical MG program is a LADDER of small launches: every level runs
its own smoother kernels with jnp transfer glue between them — exactly the
launch-bound shape the phase fusion (PR 1) removed from the step, now on
the solve side. This module closes that chain with the dynamic-extent SMEM
machinery from the shape-class kernels (ops/sor_pallas make_rb_iter_tblock
``dynamic=True``): every MG level lives on ONE fixed padded plane, its live
extents and grid-derived coefficients arrive as call-time scalars, and the
pad cells are dead globally-gated writes — levels become extents, not
programs.

Layout: a level with interior extents (jl, il) occupies the top-left
(jl+2, il+2) corner of the (Jp, Ip) plane (ghost ring included, pad cells
zero), Jp a sublane multiple of the FINEST level's rows, Ip a lane
multiple. All level transfers are gather-free: restriction is
roll(-1)/reshape-mean/roll(+1), prolongation is roll(-1)/repeat/roll(+1),
with interior masks from ``broadcasted_iota`` against the live extents, so
the same code serves every level's geometry inside one launch.

Launch structure (solo cycle, ``make_cycle_kernels``):

- DOWN kernel: for levels 0..L-2 pre-smooth, residual, restrict; emits the
  (L, ...) p/rhs level stacks.
- bottom: stays a *jnp* application between the two launches — the exact
  direct solves of the ladder (DCT diagonalization for constant
  coefficients, dense pinv for obstacle bottoms, or the FFT-preconditioned
  coarse application) are not kernel material.
- UP kernel: prolong + Neumann + post-smooth from the bottom correction
  back to the fine level.

So one V-cycle is exactly TWO pallas launches regardless of depth. The
arithmetic is op-for-op the jnp ladder's (masked where-selects instead of
mask multiplies, dead cells bitwise unchanged), so the ladder stays the
parity oracle at the ulp contract.

The class-lane variant (``make_class_cycle_2d``) goes further: the whole
cycle (including an in-kernel smoothed bottom) is ONE launch, with the
level plan itself (live flags + extents + coefficients) computed OUTSIDE
the kernel from the lane's call-time scalars (``class_level_plan``), so one
compiled cycle kernel serves every lane of a shape class.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from .sor_pallas import (
    VMEM_LIMIT_BYTES,
    CompilerParams,
    _check_dtype,
    padded_width,
)


def _pad8(n: int) -> int:
    return -(-n // 8) * 8


def fused_layout(extents) -> tuple:
    """Padded plane shape for a level hierarchy whose finest extents are
    ``extents`` ((jmax, imax) or (kmax, jmax, imax)): last dim lane-aligned,
    the rest sublane-aligned (all even, so the full-plane 2x restriction
    reshape is always legal)."""
    dims = [_pad8(e + 2) for e in extents[:-1]]
    dims.append(padded_width(extents[-1]))
    return tuple(dims)


def pad_plane(a, plane_shape):
    """Embed a (jmax+2, imax+2)[, 3-D] array at the origin of the zero
    plane (the fused layout above)."""
    out = jnp.zeros(plane_shape, a.dtype)
    return lax.dynamic_update_slice(out, a, (0,) * a.ndim)


def unpad_plane(a, extents):
    return a[tuple(slice(0, e + 2) for e in extents)]


def fused_vmem_bytes(n_levels: int, plane_shape, itemsize: int) -> int:
    """Worst-case VMEM residency of one cycle launch: the two (L, ...)
    level stacks plus the p/rhs planes and transfer temporaries."""
    return (2 * n_levels + 4) * math.prod(plane_shape) * itemsize


def plan_why_not(levels, dtype, interpret=None):
    """Reason the fused cycle cannot serve this level plan (None = it can).
    Recorded verbatim as the dispatch decision by the callers."""
    if pltpu is None:
        return "pallas TPU backend unavailable"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if len(levels) < 2:
        return ("single-level plan: the direct bottom solve is the whole "
                "cycle (ragged/odd or budget-truncated grid)")
    if not interpret and jnp.dtype(dtype).itemsize > 4:
        return "dtype not Mosaic-lowerable"
    plane = fused_layout(levels[0])
    need = fused_vmem_bytes(len(levels), plane, jnp.dtype(dtype).itemsize)
    if need > VMEM_LIMIT_BYTES:
        return (f"level stack {need >> 20} MiB exceeds the VMEM budget "
                f"({VMEM_LIMIT_BYTES >> 20} MiB) at plane {plane}")
    return None


# ----------------------------------------------------------------------
# in-kernel building blocks — full-plane forms of the ladder's interior
# ops, parametrized by live extents (traced scalars). Axis convention:
# extents/planes ordered (j, i) or (k, j, i); inv2 ordered
# [idx2, idy2(, idz2)] pairing idx2 with the LAST (lane) axis, like the
# ladder's stencils.
# ----------------------------------------------------------------------


def _iotas(shape):
    return [lax.broadcasted_iota(jnp.int32, shape, d)
            for d in range(len(shape))]


def _interior(idx, ext):
    m = None
    for ax, e in zip(idx, ext):
        t = (ax >= 1) & (ax <= e)
        m = t if m is None else m & t
    return m


def _parity_mask(idx, ext, parity):
    # plane coords ARE the ladder's 1-based interior indices (content sits
    # at the origin), so the checkerboard is the plain coordinate sum
    s = idx[0]
    for ax in idx[1:]:
        s = s + ax
    return _interior(idx, ext) & ((s % 2) == parity)


def _lap_plain(p, inv2):
    nd = p.ndim
    out = None
    for k, w in enumerate(inv2):
        ax = nd - 1 - k
        t = (jnp.roll(p, -1, ax) - 2.0 * p + jnp.roll(p, 1, ax)) * w
        out = t if out is None else out + t
    return out


def _lap_obstacle(p, fl, inv2):
    # per-direction fluid coefficients recomputed from the flag plane —
    # exact 0/1 values, so bitwise the precomputed eps arrays
    nd = p.ndim
    out = None
    for k, w in enumerate(inv2):
        ax = nd - 1 - k
        eps_p = jnp.roll(fl, -1, ax) * fl
        eps_m = jnp.roll(fl, 1, ax) * fl
        t = (eps_p * (jnp.roll(p, -1, ax) - p)
             + eps_m * (jnp.roll(p, 1, ax) - p)) * w
        out = t if out is None else out + t
    return out


def _neumann_plane(p, idx, ext, gate=None):
    """The ladder's domain-wall ghost copy (_neumann2 / neumann_faces_3d):
    each face's ghost takes the adjacent interior value, tangential ranges
    only, edges/corners untouched. All reads are interior cells of the
    original p, so the sequential where-selects are exact."""
    out = p
    for d in range(len(ext)):
        tang = None
        for d2 in range(len(ext)):
            if d2 == d:
                continue
            t = (idx[d2] >= 1) & (idx[d2] <= ext[d2])
            tang = t if tang is None else tang & t
        lo = (idx[d] == 0) & tang
        hi = (idx[d] == ext[d] + 1) & tang
        if gate is not None:
            lo = lo & gate
            hi = hi & gate
        out = jnp.where(lo, jnp.roll(p, -1, d), out)
        out = jnp.where(hi, jnp.roll(p, 1, d), out)
    return out


def _smooth_plane(p, rhs, idx, ext, parities, factor, inv2, n,
                  fl=None, fac=None, gate=None):
    """n red-black sweeps, the _smooth2/_smooth3 (plain) or
    sor_pass_obstacle (fl/fac given) arithmetic on the full plane; cells
    outside the live interior (or outside ``gate``) are bitwise
    unchanged."""
    for _ in range(n):
        for par in parities:
            m = _parity_mask(idx, ext, par)
            if gate is not None:
                m = m & gate
            if fl is None:
                r = jnp.where(m, rhs - _lap_plain(p, inv2), 0.0)
                p = p - factor * r
            else:
                pm = jnp.where(m, fl, 0.0)
                r = (rhs - _lap_obstacle(p, fl, inv2)) * pm
                p = p - fac * r
        pn = _neumann_plane(p, idx, ext)
        p = pn if gate is None else jnp.where(gate, pn, p)
    return p


def _residual_plane(p, rhs, idx, ext, inv2, fl=None, gate=None):
    m = _interior(idx, ext)
    if gate is not None:
        m = m & gate
    if fl is None:
        return jnp.where(m, rhs - _lap_plain(p, inv2), 0.0)
    pm = jnp.where(m, fl, 0.0)
    return (rhs - _lap_obstacle(p, fl, inv2)) * pm


def _restrict_plane(r, idx, ext):
    """Gather-free 2x full-weighting onto the SAME plane: interior content
    rolls to the origin, the static reshape-mean halves it (the ladder's
    _restrict2/_restrict3 reduction), and the result rolls back behind the
    coarse ghost ring. Returns the coarse rhs plane (zero ghosts — the
    ladder's _embed2)."""
    nd = r.ndim
    rs = r
    for d in range(nd):
        rs = jnp.roll(rs, -1, d)
    resh = []
    for s in r.shape:
        resh += [s // 2, 2]
    c = rs.reshape(*resh).mean(axis=tuple(range(1, 2 * nd, 2)))
    full = lax.dynamic_update_slice(jnp.zeros_like(r), c, (0,) * nd)
    for d in range(nd):
        full = jnp.roll(full, 1, d)
    ext2 = [e // 2 for e in ext]
    return jnp.where(_interior(idx, ext2), full, 0.0)


def _prolong_plane(e):
    """Gather-free 2x piecewise-constant prolongation (the ladder's
    jnp.repeat _prolong2/_prolong3); the caller masks to the fine interior
    — coarse ghost values land strictly outside it."""
    nd = e.ndim
    ec = e
    for d in range(nd):
        ec = jnp.roll(ec, -1, d)
    ec = ec[tuple(slice(0, s // 2) for s in e.shape)]
    f = ec
    for d in range(nd):
        f = jnp.repeat(f, 2, axis=d)
    for d in range(nd):
        f = jnp.roll(f, 1, d)
    return f


# ----------------------------------------------------------------------
# solo cycle: DOWN + UP kernels over a static level plan
# ----------------------------------------------------------------------


def _down_body(*refs, L, nd, n_pre, parities, masked):
    if masked:
        (ext_ref, geo_ref, fl_ref, fac_ref, p_ref, rhs_ref,
         pstk_ref, rstk_ref) = refs
    else:
        ext_ref, geo_ref, p_ref, rhs_ref, pstk_ref, rstk_ref = refs
    p = p_ref[...]
    rhs = rhs_ref[...]
    idx = _iotas(p.shape)
    for l in range(L - 1):
        ext = [ext_ref[l, d] for d in range(nd)]
        inv2 = [geo_ref[l, d] for d in range(nd)]
        factor = geo_ref[l, nd]
        fl = fl_ref[l] if masked else None
        fac = fac_ref[l] if masked else None
        p = _smooth_plane(p, rhs, idx, ext, parities, factor, inv2, n_pre,
                          fl=fl, fac=fac)
        pstk_ref[l] = p
        rstk_ref[l] = rhs
        r = _residual_plane(p, rhs, idx, ext, inv2, fl=fl)
        rhs = _restrict_plane(r, idx, ext)
        p = jnp.zeros_like(p)
    pstk_ref[L - 1] = p
    rstk_ref[L - 1] = rhs


def _up_body(*refs, L, nd, n_post, parities, masked):
    if masked:
        (ext_ref, geo_ref, fl_ref, fac_ref, pstk_ref, rstk_ref,
         pbot_ref, out_ref) = refs
    else:
        ext_ref, geo_ref, pstk_ref, rstk_ref, pbot_ref, out_ref = refs
    e = pbot_ref[...]
    idx = _iotas(e.shape)
    for l in reversed(range(L - 1)):
        ext = [ext_ref[l, d] for d in range(nd)]
        inv2 = [geo_ref[l, d] for d in range(nd)]
        factor = geo_ref[l, nd]
        fl = fl_ref[l] if masked else None
        fac = fac_ref[l] if masked else None
        p = pstk_ref[l]
        rhs = rstk_ref[l]
        f = _prolong_plane(e)
        if masked:
            f = f * fl  # inject into fluid cells only (m.p_mask)
        p = p + jnp.where(_interior(idx, ext), f, 0.0)
        p = _neumann_plane(p, idx, ext)
        p = _smooth_plane(p, rhs, idx, ext, parities, factor, inv2, n_post,
                          fl=fl, fac=fac)
        e = p
    out_ref[...] = e


def make_cycle_kernels(levels, spacings, dtype, n_pre: int = 2,
                       n_post: int = 2, interpret=None,
                       fluid_levels=None, factor_levels=None):
    """Build the two fused-cycle launches for a static level plan.

    levels: [(jl, il), ...] or [(kl, jl, il), ...], finest first, len >= 2
    (the ladder's plan — callers refuse single-level plans via
    plan_why_not). spacings: (dx, dy[, dz]). For obstacle hierarchies pass
    ``fluid_levels`` (per-level (jl+2, il+2)[...] 0/1 flag arrays, ghost
    ring fluid) and ``factor_levels`` (the per-level ObstacleMasks.factor
    interior arrays — baked verbatim so the kernel relaxes with bitwise the
    ladder's precomputed ω=1 factors).

    Returns (down, up, plane_shape):
      down(p_plane, rhs_plane) -> (p_stack, rhs_stack)   [1 launch]
      up(p_stack, rhs_stack, p_bottom_plane) -> p_plane  [1 launch]
    """
    import numpy as np

    if pltpu is None:
        raise RuntimeError("pallas TPU backend unavailable")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)
    L = len(levels)
    if L < 2:
        raise ValueError("fused cycle needs a multi-level plan")
    nd = len(levels[0])
    plane = fused_layout(levels[0])
    masked = fluid_levels is not None
    # odd-parity-first is the 3-D sweep order; red (parity 0) first in 2-D
    parities = (1, 0) if nd == 3 else (0, 1)

    ext = jnp.asarray(np.asarray(levels, np.int32))
    geo_rows = []
    for lvl in range(L):
        sp = [s * (2 ** lvl) for s in spacings]
        sq = [s * s for s in sp]
        inv2 = [1.0 / q for q in sq]
        if nd == 2:
            factor = 0.5 * (sq[0] * sq[1]) / (sq[0] + sq[1])
        else:
            factor = 0.5 * (sq[0] * sq[1] * sq[2]) / (
                sq[1] * sq[2] + sq[0] * sq[2] + sq[0] * sq[1])
        geo_rows.append(inv2 + [factor])
    geo = jnp.asarray(np.asarray(geo_rows), dtype)

    stacks = None
    if masked:
        fl_np = np.zeros((L,) + plane)
        fac_np = np.zeros((L,) + plane)
        for lvl, (flu, fac) in enumerate(zip(fluid_levels, factor_levels)):
            flu = np.asarray(flu)
            sl = tuple(slice(0, s) for s in flu.shape)
            fl_np[(lvl,) + sl] = flu.astype(np.float64)  # lint: allow(dtype-policy) host-side mask coeffs
            isl = tuple(slice(1, 1 + s) for s in np.asarray(fac).shape)
            fac_np[(lvl,) + isl] = np.asarray(fac)
        stacks = (jnp.asarray(fl_np, dtype), jnp.asarray(fac_np, dtype))

    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    zeros = (0,) * nd

    def _vspec(shape):
        n = len(shape)
        return pl.BlockSpec(shape, lambda i, _n=n: (0,) * _n)

    cp = CompilerParams(vmem_limit_bytes=VMEM_LIMIT_BYTES)
    stack_shape = (L,) + plane

    down_call = pl.pallas_call(
        functools.partial(_down_body, L=L, nd=nd, n_pre=n_pre,
                          parities=parities, masked=masked),
        grid=(1,),
        in_specs=[smem, smem]
        + ([_vspec(stack_shape)] * 2 if masked else [])
        + [_vspec(plane)] * 2,
        out_specs=[_vspec(stack_shape)] * 2,
        out_shape=[jax.ShapeDtypeStruct(stack_shape, dtype)] * 2,
        compiler_params=cp,
        interpret=interpret,
    )
    up_call = pl.pallas_call(
        functools.partial(_up_body, L=L, nd=nd, n_post=n_post,
                          parities=parities, masked=masked),
        grid=(1,),
        in_specs=[smem, smem]
        + ([_vspec(stack_shape)] * 2 if masked else [])
        + [_vspec(stack_shape)] * 2 + [_vspec(plane)],
        out_specs=[_vspec(plane)],
        out_shape=[jax.ShapeDtypeStruct(plane, dtype)],
        compiler_params=cp,
        interpret=interpret,
    )

    if masked:
        fl_stack, fac_stack = stacks

        def down(p_plane, rhs_plane):
            return down_call(ext, geo, fl_stack, fac_stack,
                             p_plane, rhs_plane)

        def up(p_stack, rhs_stack, p_bottom):
            (out,) = up_call(ext, geo, fl_stack, fac_stack,
                             p_stack, rhs_stack, p_bottom)
            return out
    else:

        def down(p_plane, rhs_plane):
            return down_call(ext, geo, p_plane, rhs_plane)

        def up(p_stack, rhs_stack, p_bottom):
            (out,) = up_call(ext, geo, p_stack, rhs_stack, p_bottom)
            return out

    return down, up, plane


# ----------------------------------------------------------------------
# class-lane cycle: the whole V-cycle in ONE launch, level plan from
# call-time scalars (fleet/shapeclass padded-class lanes)
# ----------------------------------------------------------------------


def class_level_max(jmax_c: int, imax_c: int) -> int:
    """Static unroll depth covering every lane a class can pad: an extent
    e yields at most floor(log2(e)) - 1 levels (mg_levels min_size=4)."""
    return max(1, int(math.floor(math.log2(max(8, min(jmax_c, imax_c))))) - 1)


def class_level_plan(jl, il, idx2, idy2, lmax: int, dtype,
                     min_size: int = 4):
    """The mg_levels rule as jnp over the lane's call-time extents: level
    l+1 is live while level l's extents are even and >= 2*min_size.
    Returns (ext (lmax, 3) int32 rows [jl, il, live],
    geo (lmax, 3) dtype rows [idx2, idy2, factor])."""
    jl = jnp.asarray(jl, jnp.int32)
    il = jnp.asarray(il, jnp.int32)
    idx2 = jnp.asarray(idx2, dtype)
    idy2 = jnp.asarray(idy2, dtype)
    live = jnp.asarray(1, jnp.int32)
    ext_rows, geo_rows = [], []
    for lvl in range(lmax):
        scale = jnp.asarray(4.0 ** lvl, dtype)
        i2, j2 = idx2 / scale, idy2 / scale
        ext_rows.append(jnp.stack([jl, il, live]))
        geo_rows.append(jnp.stack([i2, j2, 0.5 / (i2 + j2)]))
        can = ((jl % 2 == 0) & (il % 2 == 0)
               & (jl >= 2 * min_size) & (il >= 2 * min_size))
        live = live * can.astype(jnp.int32)
        jl = jl // 2
        il = il // 2
    return jnp.stack(ext_rows), jnp.stack(geo_rows)


def _class_cycle_body(ext_ref, geo_ref, p_ref, rhs_ref, out_ref, res_ref,
                      *, lmax, n_pre, n_post, n_bottom):
    p = p_ref[...]
    rhs = rhs_ref[...]
    idx = _iotas(p.shape)
    parities = (0, 1)
    p_lv, rhs_lv, exts, geos, lives = [], [], [], [], []
    for l in range(lmax):
        ext = [ext_ref[l, 0], ext_ref[l, 1]]
        inv2 = [geo_ref[l, 0], geo_ref[l, 1]]
        factor = geo_ref[l, 2]
        live = ext_ref[l, 2] > 0
        p = _smooth_plane(p, rhs, idx, ext, parities, factor, inv2, n_pre,
                          gate=live)
        p_lv.append(p)
        rhs_lv.append(rhs)
        exts.append(ext)
        geos.append((inv2, factor))
        lives.append(live)
        r = _residual_plane(p, rhs, idx, ext, inv2, gate=live)
        rhs = _restrict_plane(r, idx, ext)
        p = jnp.zeros_like(p)
    e = jnp.zeros_like(p)
    for l in reversed(range(lmax)):
        ext = exts[l]
        inv2, factor = geos[l]
        live = lives[l]
        child = lives[l + 1] if l + 1 < lmax else jnp.asarray(False)
        is_bottom = live & jnp.logical_not(child)
        p = p_lv[l]
        rhs = rhs_lv[l]
        f = _prolong_plane(e)
        p = p + jnp.where(_interior(idx, ext) & child, f, 0.0)
        p = jnp.where(child, _neumann_plane(p, idx, ext), p)
        # the deepest live level replaces the direct solve with extra
        # smoothing — the class cycle's in-kernel bottom
        p = _smooth_plane(p, rhs, idx, ext, parities, factor, inv2,
                          n_bottom, gate=is_bottom)
        p = _smooth_plane(p, rhs, idx, ext, parities, factor, inv2,
                          n_post, gate=live)
        e = jnp.where(live, p, e)
    ext0 = [ext_ref[0, 0], ext_ref[0, 1]]
    inv20 = [geo_ref[0, 0], geo_ref[0, 1]]
    r = _residual_plane(e, rhs_lv[0], idx, ext0, inv20)
    res_ref[0, 0] = jnp.sum(r * r)
    out_ref[...] = e


def make_class_cycle_2d(jmax_c: int, imax_c: int, dtype, n_pre: int = 2,
                        n_post: int = 2, n_bottom: int = 8,
                        interpret=None):
    """One-launch dynamic-extent V-cycle for a padded shape class.

    Returns (cycle, plane_shape, lmax) with
    ``cycle(p_plane, rhs_plane, ext, geo) -> (p_plane, res_sumsq)`` where
    (ext, geo) come from class_level_plan at the lane's live extents. The
    fine-level residual sum-of-squares rides back through SMEM so the
    convergence loop costs no extra launch."""
    if pltpu is None:
        raise RuntimeError("pallas TPU backend unavailable")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)
    lmax = class_level_max(jmax_c, imax_c)
    plane = fused_layout((jmax_c, imax_c))

    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    call = pl.pallas_call(
        functools.partial(_class_cycle_body, lmax=lmax, n_pre=n_pre,
                          n_post=n_post, n_bottom=n_bottom),
        grid=(1,),
        in_specs=[smem, smem,
                  pl.BlockSpec(plane, lambda i: (0, 0)),
                  pl.BlockSpec(plane, lambda i: (0, 0))],
        out_specs=[
            pl.BlockSpec(plane, lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(plane, dtype),
            jax.ShapeDtypeStruct((1, 1), dtype),
        ],
        compiler_params=CompilerParams(vmem_limit_bytes=VMEM_LIMIT_BYTES),
        interpret=interpret,
    )

    def cycle(p_plane, rhs_plane, ext, geo):
        p_out, res = call(ext, geo, p_plane, rhs_plane)
        return p_out, res[0, 0]

    return cycle, plane, lmax


# ----------------------------------------------------------------------
# probe — one-time real-backend smoke (the probe_pallas contract)
# ----------------------------------------------------------------------

_PROBE_OK = None


def probe_mg_fused() -> bool:
    """Compile and run a tiny two-level fused cycle on the real backend
    once per process; any failure (missing Mosaic op, lowering error)
    makes every caller fall back to the jnp ladder."""
    global _PROBE_OK
    if _PROBE_OK is None:
        try:
            levels = [(16, 16), (8, 8)]
            down, up, plane = make_cycle_kernels(
                levels, (1.0 / 16, 1.0 / 16), jnp.float32,
                interpret=False,
            )
            p = pad_plane(jnp.zeros((18, 18), jnp.float32), plane)
            r = pad_plane(jnp.ones((18, 18), jnp.float32), plane)
            pstk, rstk = down(p, r)
            out = up(pstk, rstk, jnp.zeros_like(p))
            jax.block_until_ready(out)
            _PROBE_OK = True
        except Exception as exc:  # lint: allow(broad-except) — probe contract: any failure means "don't dispatch"
            import warnings

            warnings.warn(
                f"fused MG cycle kernel unavailable ({type(exc).__name__}); "
                "falling back to the jnp ladder",
                stacklevel=2,
            )
            _PROBE_OK = False
    return _PROBE_OK
