from .sor import (
    checkerboard_mask,
    sor_pass,
    neumann_bc,
    residual_all,
)
