"""Per-shard Pallas kernel for the DISTRIBUTED flag-masked (obstacle) SOR.

Completes the kernel-per-shard family (ops/sor_qdist.py quarters 2-D,
ops/sor_odist.py octants 3-D): the obstacle configs use the masked
CHECKERBOARD layout (compressed layouts don't carry flag fields), so this
is the masked mode of sor_pallas._tblock_kernel generalized to a shard of a
("j","i") mesh — masks from GLOBAL coordinates via scalar prefetch, updates
clipped to the stored block with a frozen outermost ring, owned-only
residual, and per-direction fluid coefficients computed in-kernel from the
shard's deep flag block (identical flag VALUES on every shard that sees a
cell, so redundant halo recompute stays consistent — the CA discipline of
ops/obstacle.make_dist_obstacle_solver, whose jnp path ca_rb_iters_obstacle
is this kernel's twin).

Layout: the (jl+2H, il+2H) deep-halo extended block (H = 2n grid cells) in
sor_pallas's padded layout (pad_array with halo = tblock_halo(n)); cell
(a, b) of the extended block holds global extended index
(a - H + joff + 1, b - H + ioff + 1) — ghost row gj = 0 is the physical
wall. One call performs n red-black iterations + globally-gated Neumann
wall refresh — exactly the validity one depth-2n halo_exchange provides.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .sor_pallas import (
    CompilerParams,
    VMEM_LIMIT_BYTES,
    _check_dtype,
    masked_stencil_ops,
    padded_width,
    pick_block_rows_tblock,
    pltpu,
    rb_inner_sweeps,
    tblock_feasible,
    tblock_halo,
    tblock_vmem_bytes,
)


def _obsdist_kernel(
    sref,   # SMEM scalar prefetch: int32[2] = (joff, ioff) grid offsets
    p_in,   # ANY padded deep block
    rhs,    # ANY
    flg,    # ANY padded deep fluid flags (0/1)
    p_out,  # ANY
    res,    # SMEM (1, 1)
    pw2,    # VMEM (2, br+2h, wp)
    rw2,    # VMEM (2, br+2h, wp)
    fw2,    # VMEM (2, br+2h, wp)
    ob2,    # VMEM (2, br, wp)
    vacc,   # VMEM (1, wp)
    ld_sem,  # DMA (2, 3)
    st_sem,  # DMA (2,)
    *,
    n_inner: int,
    block_rows: int,
    nblocks: int,
    gjmax: int,
    gimax: int,
    jl: int,
    il: int,
    H: int,      # deep-halo depth in grid cells (= 2*n_inner)
    halo: int,   # window halo (>= H, sublane-aligned)
    omega: float,
    idx2: float,
    idy2: float,
    loop_sweeps: bool = False,
):
    b = pl.program_id(0)
    br = block_rows
    h = halo
    slot = b % 2
    nslot = (b + 1) % 2
    joff = sref[0]
    ioff = sref[1]

    def load(k, s):
        return [
            pltpu.make_async_copy(
                p_in.at[pl.ds(k * br, br + 2 * h), :], pw2.at[s],
                ld_sem.at[s, 0]),
            pltpu.make_async_copy(
                rhs.at[pl.ds(k * br, br + 2 * h), :], rw2.at[s],
                ld_sem.at[s, 1]),
            pltpu.make_async_copy(
                flg.at[pl.ds(k * br, br + 2 * h), :], fw2.at[s],
                ld_sem.at[s, 2]),
        ]

    def store(k, s):
        return pltpu.make_async_copy(
            ob2.at[s], p_out.at[pl.ds(h + k * br, br)], st_sem.at[s]
        )

    @pl.when(b == 0)
    def _():
        res[0, 0] = jnp.zeros((), res.dtype)
        vacc[...] = jnp.zeros_like(vacc)
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    p = pw2[slot]
    rw = rw2[slot]
    fl = fw2[slot]

    # padded row of window cell (w, c): rho = b*br + w; local deep index
    # a = rho - h; global extended index gj = a - H + joff + 1, gi likewise
    rho = b * br + jax.lax.broadcasted_iota(jnp.int32, p.shape, 0)
    ccol = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
    a_j = rho - h
    a_i = ccol
    gj = a_j - H + joff + 1
    gi = a_i - H + ioff + 1
    interior = (gj >= 1) & (gj <= gjmax) & (gi >= 1) & (gi <= gimax)
    # freeze the outermost stored ring (its neighbours are dead padding —
    # same CA equivalence as parallel/quarters_dist.q_masks)
    valid_upd = (
        (a_j >= 1) & (a_j <= jl + 2 * H - 2)
        & (a_i >= 1) & (a_i <= il + 2 * H - 2)
    )
    fluid = fl != 0
    red = interior & (((gi + gj) % 2) == 0) & fluid & valid_upd
    black = interior & (((gi + gj) % 2) == 1) & fluid & valid_upd
    # globally-gated Neumann wall refresh, tangentially clipped
    tan_i = (gi >= 1) & (gi <= gimax)
    tan_j = (gj >= 1) & (gj <= gjmax)
    row_ghost_lo = (gj == 0) & tan_i & valid_upd
    row_ghost_hi = (gj == gjmax + 1) & tan_i & valid_upd
    col_ghost_lo = (gi == 0) & tan_j & valid_upd
    col_ghost_hi = (gi == gimax + 1) & tan_j & valid_upd
    # owned region for the residual (static layout bounds)
    owned = (
        (a_j >= H) & (a_j < H + jl) & (a_i >= H) & (a_i < H + il)
    )

    # shared masked-stencil math + inner loop (sor_pallas — one home, so
    # this kernel and _tblock_kernel's masked mode cannot drift)
    fac, lap = masked_stencil_ops(fl, idx2, idy2, omega)
    p, r_red, r_blk = rb_inner_sweeps(
        p, rw, n_inner, red, black, fac, lap,
        (row_ghost_lo, row_ghost_hi, col_ghost_lo, col_ghost_hi),
        loop=loop_sweeps,
    )

    @pl.when(b >= 2)
    def _():
        store(b - 2, slot).wait()

    ob2[slot] = p[h: h + br, :]
    store(b, slot).start()

    ro = jnp.where(owned, r_red * r_red + r_blk * r_blk, 0.0)
    vacc[...] += jnp.sum(ro[h: h + br, :], axis=0, keepdims=True)

    @pl.when(b == nblocks - 1)
    def _():
        res[0, 0] += jnp.sum(vacc[...])
        store(b, slot).wait()
        if nblocks > 1:
            store(b - 1, nslot).wait()


def make_rb_iters_obsdist(jmax, imax, jl, il, n, dx, dy, omega, dtype, *,
                          interpret: bool | None = None,
                          block_rows: int | None = None,
                          ragged: bool = False,
                          loop_sweeps: bool = False):
    """Build `(offs_i32[2], p_padded, rhs_padded, flg_padded) ->
    (p_padded', owned res sum of last iter)` performing n red-black
    eps-coefficient iterations on the padded (jl+2H, il+2H) deep block
    (H = ca_halo(n, ragged) = 2n, or 2n+1 on ragged decompositions — the
    wall-ghost refresh of a trailing/dead shard consumes one extra layer,
    parallel/stencil2d.ca_halo; pad with sor_pallas.pad_array(x,
    block_rows, halo)). The kernel body is global-coordinate gated
    throughout, so ragged layouts need no body change — dead cells beyond
    the global ghost ring sit outside `interior` and carry zero flags.
    Returns (rb_iters, block_rows, halo). offs = [joff, ioff] grid
    offsets. block_rows overrides the picker (tests use it to force the
    multi-block DMA pipeline on small geometries)."""
    from ..parallel.stencil2d import ca_halo

    if pltpu is None:
        return None, 0, 0
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if loop_sweeps and not interpret:
        # the looped (scf.for) kernel is bitwise-correct in interpret mode
        # but CRASHES the production Mosaic compiler at any depth on the
        # current toolchain (round-5 measured outcome, see the depth note
        # below) — a compile-time crash is not catchable by the dispatch
        # backoff, so refuse here instead of letting the opt-in reach the
        # real compiler (ADVICE round-5 item)
        raise ValueError(
            "loop_sweeps=True is an interpret-mode-only form: the scf.for "
            "sweep loop crashes the production Mosaic compiler (round-5 "
            "record, results/obsdist2048.json); use the unrolled default "
            "on TPU"
        )
    _check_dtype(dtype, interpret)
    H = ca_halo(n, ragged)
    ext_j = jl + 2 * H  # logical rows of the deep block incl. its "+2"
    ext_i = il + 2 * H
    h = tblock_halo(n, dtype)
    if h < H:  # ragged's +1 layer crossed a sublane-alignment boundary
        from .sor_pallas import _align

        a = _align(dtype)
        h = -(-H // a) * a
    if block_rows is None:
        block_rows = pick_block_rows_tblock(ext_j - 2, ext_i - 2, dtype, n)
    wp = padded_width(ext_i - 2)
    itemsize = jnp.dtype(dtype).itemsize
    if not tblock_feasible(block_rows, h, wp, itemsize, masked=True):
        raise ValueError(
            f"obstacle-dist scratch {tblock_vmem_bytes(block_rows, h, wp, itemsize, True) >> 20} MiB "
            f"exceeds the VMEM budget (block_rows={block_rows}, h={h}, "
            f"wp={wp}); reduce tpu_ca_inner or the shard width"
        )
    # Mosaic's STACK for the unrolled n-sweep body scales with n in a way
    # the declared-scratch formula cannot see (each unrolled sweep keeps
    # window-sized temporaries live). Empirical anchor on v5e f32 at a
    # 512x2048 shard: n=16 OOMs the scoped vmem at compile (122.05M vs
    # 117.53M) while n=8 compiles and runs; ~(n+8) live window-sized
    # buffers reproduces both points. Raise a CATCHABLE error so the
    # dispatcher can back off the depth instead of crashing at compile.
    #
    # Round 5 tried the obvious fix — WINDOW the sweeps through scf.for
    # (rb_inner_sweeps(loop=True)), whose live set is one sweep's
    # regardless of n. MEASURED OUTCOME (VERDICT r4 item 7, the
    # "documented loss" arm): the looped kernel is bitwise-correct in
    # interpret mode (tests/test_quarters_dist.py windowed-sweeps test)
    # but CRASHES the production Mosaic compiler at ANY depth on the
    # current toolchain (tpu_compile_helper subprocess exit 1 at n=8 and
    # n=16, 512x2048 shard, same session in which the unrolled n=8 kernel
    # measured 21.0G). So `loop_sweeps` stays an EXPLICIT opt-in for
    # interpret/tests, auto mode keeps the unrolled form + depth backoff,
    # and the depth-16 co-tune remains closed off by the toolchain, not by
    # this kernel's structure.
    window = (block_rows + 2 * h) * wp * itemsize
    if not loop_sweeps and window * (n + 8) > VMEM_LIMIT_BYTES:
        raise ValueError(
            f"obstacle-dist unrolled-sweep stack estimate "
            f"{(window * (n + 8)) >> 20} MiB exceeds the VMEM budget at "
            f"depth n={n} (window {window >> 20} MiB); reduce the depth"
        )
    nblocks = -(-ext_j // block_rows)
    rp = nblocks * block_rows + 2 * h
    kernel = functools.partial(
        _obsdist_kernel,
        n_inner=n,
        block_rows=block_rows,
        nblocks=nblocks,
        gjmax=jmax,
        gimax=imax,
        jl=jl,
        il=il,
        H=H,
        halo=h,
        omega=omega,
        idx2=1.0 / (dx * dx),
        idy2=1.0 / (dy * dy),
        loop_sweeps=loop_sweeps,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, block_rows + 2 * h, wp), dtype),
            pltpu.VMEM((2, block_rows + 2 * h, wp), dtype),
            pltpu.VMEM((2, block_rows + 2 * h, wp), dtype),
            pltpu.VMEM((2, block_rows, wp), dtype),
            pltpu.VMEM((1, wp), dtype),
            pltpu.SemaphoreType.DMA((2, 3)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((rp, wp), dtype),
            jax.ShapeDtypeStruct((1, 1), dtype),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )

    def rb_iters(offs, p_padded, rhs_padded, flg_padded):
        p_padded, r = call(offs, p_padded, rhs_padded, flg_padded)
        return p_padded, r[0, 0]

    return rb_iters, block_rows, h


def padded_deep_exchange(xp, comm, H, row0, ext_j, ext_i):
    """halo_exchange(depth=H) operating directly on the PADDED layout, so
    the solve loop can carry the padded array and pay pad/unpad once per
    SOLVE instead of once per body iteration (the dominant envelope cost at
    small shard sizes). Logical deep-block rows live at padded rows
    [row0, row0+ext_j), cols at [0, ext_i); same ppermute choreography and
    PROC_NULL masking as parallel/comm._exchange_axis with static offsets."""
    from jax import lax

    from ..parallel.comm import _nbr_perm

    nper = comm.axis_size("j")
    if nper > 1:
        idx = lax.axis_index("j")
        lo_g, hi_g = row0, row0 + ext_j - H
        lo_o, hi_o = row0 + H, row0 + ext_j - 2 * H
        from_lo = lax.ppermute(
            xp[hi_o:hi_o + H], "j", _nbr_perm(nper, True, False)
        )
        from_hi = lax.ppermute(
            xp[lo_o:lo_o + H], "j", _nbr_perm(nper, False, False)
        )
        from_lo = jnp.where(idx > 0, from_lo, xp[lo_g:lo_g + H])
        from_hi = jnp.where(idx < nper - 1, from_hi, xp[hi_g:hi_g + H])
        xp = xp.at[lo_g:lo_g + H].set(from_lo)
        xp = xp.at[hi_g:hi_g + H].set(from_hi)

    nper = comm.axis_size("i")
    if nper > 1:
        idx = lax.axis_index("i")
        lo_g, hi_g = 0, ext_i - H
        lo_o, hi_o = H, ext_i - 2 * H
        from_lo = lax.ppermute(
            xp[:, hi_o:hi_o + H], "i", _nbr_perm(nper, True, False)
        )
        from_hi = lax.ppermute(
            xp[:, lo_o:lo_o + H], "i", _nbr_perm(nper, False, False)
        )
        from_lo = jnp.where(idx > 0, from_lo, xp[:, lo_g:lo_g + H])
        from_hi = jnp.where(idx < nper - 1, from_hi, xp[:, hi_g:hi_g + H])
        xp = xp.at[:, lo_g:lo_g + H].set(from_lo)
        xp = xp.at[:, hi_g:hi_g + H].set(from_hi)
    return xp
