"""Fused NS-2D step-phase Pallas kernels — the non-solve timestep in two
HBM sweeps.

The round-5 north-star decomposition (results/northstar_dcavity4096.json)
isolated the blocker on the >=10x wall-clock bar: the pressure solve runs at
kernel rate, but the ~40-launch jnp phase chain around it (BCs + special BC
+ computeFG + RHS + adaptUV + CFL max) costs 6.4 ms/step against a ~0.8 ms
HBM-traffic floor — pure per-launch overhead. This module fuses that chain
into TWO kernels bracketing the solve, the same fixed-overhead-amortization
move the temporal-blocked SOR kernels made for the solve itself (and the
reference's comm/compute-overlap lesson one level down: launch latency
instead of message latency):

  PRE  (u, v, dt)        -> (u', v', F, G, rhs)
       wall BCs -> special BC -> obstacle velocity BC -> F/G predictor
       + wall fixups -> obstacle F/G mask -> Poisson RHS
  POST (u', v', F, G, p, dt) -> (u'', v'', max|u''|, max|v''|)
       projection adaptUV (+ obstacle face mask) + the CFL max reduction

The CFL max of the NEXT step is folded into POST: the step state carries
(umax, vmax) and the timestep becomes pure scalar math (ops/ns2d.cfl_dt).
max is exact under any reduction order, and adaptUV is the last writer of
u/v in a step, so max-at-end-of-step == max-at-start-of-next-step bitwise.

Equivalence policy (the quarters-kernel precedent, ops/sor_quarters.py):
every formula is the SAME function the jnp ops call (ops/ns2d
fg_predictor_terms / rhs_terms / adapt_terms with the kernel window's
roll), wall BCs are sequential where-updates writing the same values in
the same wall order as set_boundary_conditions, and all writes are gated
by GLOBAL coordinates — the discipline of ops/sor_obsdist.py, which makes
one kernel serve both the single-device solvers (offsets 0, block = whole
grid) and the distributed twins (per-shard deep-halo blocks, offsets via
scalar prefetch). Pure-copy phases (BC strips, the masked selects, the max
reductions given equal inputs) are BITWISE identical to the jnp chain; the
compound F/G/RHS/projection arithmetic is ulp-equivalent — the same ops in
the same order, differing only by compiler fusion (fma contraction), the
measured-and-accepted gap between ANY two XLA compilations of the same
formula (jit vs eager of the identical jnp function already differs at the
last ulp on CPU). Parity tests pin copies with array_equal and compound
terms at ulp-scale tolerances (tests/test_ns2d_fused.py).

Layout: the sor_pallas padded layout (pad_array/unpad_array, halo =
sublane alignment >= the 3-row validity chain BC->obstacleBC->FG->RHS).
Distributed callers pass the deep-halo extended block (jl + 2H rows,
H = FUSE_DEEP_HALO: cell (a, b) holds global extended index
(joff + a - H + 1, ...) — the stencil2d embed_deep convention) after one
depth-H exchange per step.

Obstacle flag fields compose branch-free: the padded 0/1 fluid flag rides
as a third input window and u_face/v_face are derived in-kernel from it
(integer-exact, matching ops/obstacle.make_masks including the
ghost-column wrap fix), so the obstacle velocity BC, F/G face mask and
projection face mask are the same flag-multiply forms the jnp path uses.
Single-device callers bake the global flag in as a padded constant
(`fluid=<array>`); distributed callers pass `fluid=True` and feed the
per-shard deep-halo slice of the global flag at call time (the
ops/sor_obsdist global-constant-slice convention — sliced blocks agree
wherever shards overlap, so redundant halo recompute stays consistent).

Ragged (pad-with-mask) shards are the SAME kernels at uneven block
bounds: every write is already global-coordinate-gated (hi walls sit
anywhere inside a trailing shard, exactly parallel/ragged2d.py's masked
forms), and POST(ragged=True) appends the live-mask multiply that zeroes
dead cells after the projection — the one extra op the jnp ragged chain
does (live_masks) so pad-cell garbage never reaches the ghost-inclusive
CFL scan.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import ns2d as ops
from .sor_pallas import (
    VMEM_LIMIT_BYTES,
    CompilerParams,
    _align,
    _check_dtype,
    pad_array,
    padded_width,
    pick_block_rows_tblock,
    pltpu,
    unpad_array,
)

NOSLIP, SLIP, OUTFLOW, PERIODIC = 1, 2, 3, 4

# validity consumed between the raw u/v window and the RHS: wall BC (reads
# <=1 cell), obstacle velocity BC (<=1), F/G predictor (<=1), RHS (<=1 but
# only on the low side) — 3 layers bound the chain stage-by-stage
FUSE_CHAIN = 3
# the MEASURED access footprint of the composed chain
# (halocheck.pre_chain_footprint, pinned by tests/test_analysis.py):
# RHS reads F/G only same-row/low-side and G reads u only northward, so
# no composed read path consumes all three budgeted layers — 2 is what
# the deep exchange must actually cover. A chain edit that widens the
# footprint fails halocheck's PRE entries (declared = FUSE_FOOTPRINT)
# before any distributed run can corrupt.
FUSE_FOOTPRINT = 2
# deep-halo exchange depth: the measured footprint plus the extended
# block's own ghost layer (which the depth-H exchange refreshes on
# partitioned axes). Was FUSE_CHAIN + 1 = 4 until the footprint
# derivation shrank it (ROADMAP carried-forward): one whole strip layer
# of exchange bytes saved on every dist step.
FUSE_DEEP_HALO = FUSE_FOOTPRINT + 1
# comm/compute overlap (parallel/overlap.py): extended-block cells at
# least this far from the block edge have a dependency cone (measured
# footprint FUSE_FOOTPRINT) that never reaches the exchanged deep-halo
# strips — the interior half of the split PRE call is gated to them
# (analysis/halocheck.py overlap-interior entries)
OVERLAP_RIM = FUSE_FOOTPRINT + 1


def fuse_halo(dtype) -> int:
    """Window halo rows: the 3-row validity chain rounded to the DMA
    sublane alignment (pass to pad_array/unpad_array)."""
    return max(_align(dtype), FUSE_CHAIN)


def apply_wall_bcs_2d(u, v, gj, gi, bc, gjmax, gimax, roll=jnp.roll):
    """setBoundaryConditions (ops/ns2d.set_boundary_conditions) as
    sequential global-coordinate-gated where-updates: same wall order
    (left, right, bottom, top), same written values, so later walls read
    earlier walls' writes exactly like the at[].set chain. `gj`/`gi` are
    global-extended-index arrays of the window cells."""
    bc_left, bc_right, bc_bottom, bc_top = bc
    rows = (gj >= 1) & (gj <= gjmax)
    cols = (gi >= 1) & (gi <= gimax)
    zero = jnp.zeros((), u.dtype)

    m = (gi == 0) & rows  # left wall: U on the wall, V ghost
    if bc_left == NOSLIP:
        u = jnp.where(m, zero, u)
        v = jnp.where(m, -roll(v, -1, axis=1), v)
    elif bc_left == SLIP:
        u = jnp.where(m, zero, u)
        v = jnp.where(m, roll(v, -1, axis=1), v)
    elif bc_left == OUTFLOW:
        u = jnp.where(m, roll(u, -1, axis=1), u)
        v = jnp.where(m, roll(v, -1, axis=1), v)
    mw = (gi == gimax) & rows   # right wall: U(imax) on the wall
    mg = (gi == gimax + 1) & rows  # right ghost column
    if bc_right == NOSLIP:
        u = jnp.where(mw, zero, u)
        v = jnp.where(mg, -roll(v, 1, axis=1), v)
    elif bc_right == SLIP:
        u = jnp.where(mw, zero, u)
        v = jnp.where(mg, roll(v, 1, axis=1), v)
    elif bc_right == OUTFLOW:
        u = jnp.where(mw, roll(u, 1, axis=1), u)
        v = jnp.where(mg, roll(v, 1, axis=1), v)
    m = (gj == 0) & cols  # bottom wall: V on the wall, U ghost
    if bc_bottom == NOSLIP:
        v = jnp.where(m, zero, v)
        u = jnp.where(m, -roll(u, -1, axis=0), u)
    elif bc_bottom == SLIP:
        v = jnp.where(m, zero, v)
        u = jnp.where(m, roll(u, -1, axis=0), u)
    elif bc_bottom == OUTFLOW:
        u = jnp.where(m, roll(u, -1, axis=0), u)
        v = jnp.where(m, roll(v, -1, axis=0), v)
    mw = (gj == gjmax) & cols    # top wall: V(jmax) on the wall
    mg = (gj == gjmax + 1) & cols  # top ghost row
    if bc_top == NOSLIP:
        v = jnp.where(mw, zero, v)
        u = jnp.where(mg, -roll(u, 1, axis=0), u)
    elif bc_top == SLIP:
        v = jnp.where(mw, zero, v)
        u = jnp.where(mg, roll(u, 1, axis=0), u)
    elif bc_top == OUTFLOW:
        u = jnp.where(mg, roll(u, 1, axis=0), u)
        v = jnp.where(mw, roll(v, 1, axis=0), v)
    return u, v


def apply_special_bc_2d(u, gj, gi, problem, gjmax, gimax, dy, ylength,
                        dtype, prof_dtype, roll=jnp.roll):
    """set_special_bc_dcavity / set_special_bc_canal in gated-where form.
    `prof_dtype` is the dtype the canal profile's y-coordinate math runs in
    before the cast to the field dtype — the field dtype for the
    single-device twin, the time/index dtype for the distributed one (both
    jnp twins' exact expressions)."""
    if problem == "dcavity":
        # lid skips the LAST interior i (the reference loop-bound quirk)
        m = (gj == gjmax + 1) & (gi >= 1) & (gi <= gimax - 1)
        u = jnp.where(m, 2.0 - roll(u, 1, axis=0), u)
    elif problem in ("canal", "canal_obstacle"):
        m = (gi == 0) & (gj >= 1) & (gj <= gjmax)
        y = ((gj.astype(prof_dtype) - 0.5) * dy).astype(dtype)
        prof = y * (ylength - y) * 4.0 / (ylength * ylength)
        u = jnp.where(m, prof, u)
    return u


def _obstacle_faces(fl, gj, gi, gjmax, gimax, roll=jnp.roll):
    """u_face/v_face derived from the 0/1 fluid flag window — integer-exact
    parity with ops/obstacle.make_masks (incl. its ghost-column/row
    wrap-fix: the last global ghost column/row is forced to a face)."""
    one = jnp.ones((), fl.dtype)
    u_face = jnp.where(gi == gimax + 1, one, fl * roll(fl, -1, axis=1))
    v_face = jnp.where(gj == gjmax + 1, one, fl * roll(fl, -1, axis=0))
    return u_face, v_face


def apply_obstacle_velocity_bc_window(u, v, fl, u_face, v_face,
                                      roll=jnp.roll):
    """ops/obstacle.apply_obstacle_velocity_bc transcribed on the window
    (same flag-multiply arithmetic; every wrapped read the full-array form
    relies on is multiplied by zero at the cells that could see window
    wrap, same as at the jnp path's array edges)."""
    one = jnp.ones((), u.dtype)
    u = u * u_face
    v = v * v_face
    both_obs_u = (one - fl) * (one - roll(fl, -1, axis=1))
    uf_n = roll(u_face, -1, axis=0)
    uf_s = roll(u_face, 1, axis=0)
    u_n = roll(u, -1, axis=0)
    u_s = roll(u, 1, axis=0)
    u = u + both_obs_u * (uf_n * (-u_n) + (one - uf_n) * uf_s * (-u_s))
    both_obs_v = (one - fl) * (one - roll(fl, -1, axis=0))
    vf_e = roll(v_face, -1, axis=1)
    vf_w = roll(v_face, 1, axis=1)
    v_e = roll(v, -1, axis=1)
    v_w = roll(v, 1, axis=1)
    v = v + both_obs_v * (vf_e * (-v_e) + (one - vf_e) * vf_w * (-v_w))
    return u, v


def _pre_kernel(
    sref,    # SMEM scalar prefetch: int32[2] = (joff, ioff) grid offsets
    dt_ref,  # SMEM (1, 1): the timestep
    *refs,   # [u_in, v_in(, flg)] + [u_out, v_out, f_out, g_out, r_out] + scratch
    block_rows: int,
    nblocks: int,
    gjmax: int,
    gimax: int,
    ljmax: int,   # local interior extents (== gjmax/gimax single-device)
    limax: int,
    ext_pad: int,  # deep layers beyond the extended block (dist: H-1)
    halo: int,
    bc: tuple,
    problem: str | None,
    re: float,
    gx: float,
    gy: float,
    gamma: float,
    dx: float,
    dy: float,
    ylength: float,
    prof_dtype,
    masked: bool,
    bands: tuple | None = None,
    dynamic: bool = False,
):
    if dynamic:
        # shape-class mode (fleet/shapeclass.py): the live extents and the
        # per-lane cell sizes arrive as SMEM scalars after dt, so one
        # compiled kernel at the padded CLASS geometry serves every lane
        # (every write below is already gated by the SAME comparisons)
        ext_ref, geo_ref, *refs = refs
    if masked:
        (u_in, v_in, flg, u_out, v_out, f_out, g_out, r_out,
         uw2, vw2, fw2, ob2, ld_sem, st_sem) = refs
    else:
        (u_in, v_in, u_out, v_out, f_out, g_out, r_out,
         uw2, vw2, ob2, ld_sem, st_sem) = refs
        flg = fw2 = None
    b = pl.program_id(0)
    br = block_rows
    h = halo
    slot = b % 2
    nslot = (b + 1) % 2
    joff = sref[0]
    ioff = sref[1]
    dt = dt_ref[0, 0]
    if dynamic:
        # single-device class lanes: local extents == global extents
        gjmax = ext_ref[0, 0]
        gimax = ext_ref[0, 1]
        ljmax = gjmax
        limax = gimax
        dx = geo_ref[0, 0]
        dy = geo_ref[0, 1]

    # banded (grid-restricted) sweeps (`tpu_overlap_restrict`,
    # parallel/overlap.region_plan): grid step k of band (s, n) covers
    # padded rows [s + j*br, ...) instead of [k*br, ...). The full-sweep
    # default keeps the literal k*br indexing, so the unrestricted
    # program traces byte-identically to the historical kernel.
    if bands is None or (len(bands) == 1 and bands[0][0] == 0):
        def row_of(k):
            return k * br
    else:
        def row_of(k):
            row, acc = None, 0
            for s, n in bands:
                r = s + (k - acc) * br
                row = r if row is None else jnp.where(k >= acc, r, row)
                acc += n
            return row

    def load(k, s):
        r0 = row_of(k)
        copies = [
            pltpu.make_async_copy(
                u_in.at[pl.ds(r0, br + 2 * h), :], uw2.at[s],
                ld_sem.at[s, 0]),
            pltpu.make_async_copy(
                v_in.at[pl.ds(r0, br + 2 * h), :], vw2.at[s],
                ld_sem.at[s, 1]),
        ]
        if masked:
            copies.append(pltpu.make_async_copy(
                flg.at[pl.ds(r0, br + 2 * h), :], fw2.at[s],
                ld_sem.at[s, 2]))
        return copies

    def store(k, s):
        r0 = row_of(k)
        outs = (u_out, v_out, f_out, g_out, r_out)
        return [
            pltpu.make_async_copy(
                ob2.at[s, q], outs[q].at[pl.ds(h + r0, br)],
                st_sem.at[s, q])
            for q in range(5)
        ]

    @pl.when(b == 0)
    def _():
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    u = uw2[slot]
    v = vw2[slot]

    # padded row of window cell (w, c): rho = row_of(b) + w; global
    # extended index gj = (rho - h) - ext_pad + joff (ext_pad = 0 single-
    # device, H-1 on deep-halo dist blocks), gi likewise (columns are
    # unshifted)
    rho = row_of(b) + jax.lax.broadcasted_iota(jnp.int32, u.shape, 0)
    a_j = rho - h
    a_i = jax.lax.broadcasted_iota(jnp.int32, u.shape, 1)
    gj = a_j - ext_pad + joff
    gi = a_i - ext_pad + ioff

    # restore the dead-cell-zero invariant on the loaded windows: the
    # carried padded arrays' halo/tail rows are never stored by this
    # kernel, so they hold undefined data (NaN in interpret mode) — and
    # the obstacle path's MULTIPLICATIVE masks propagate 0*NaN into valid
    # cells where jnp.where would not
    ext_rows = ljmax + 2 + 2 * ext_pad
    ext_cols = limax + 2 + 2 * ext_pad
    live_in = (a_j >= 0) & (a_j < ext_rows) & (a_i >= 0) & (a_i < ext_cols)
    u = jnp.where(live_in, u, 0.0)
    v = jnp.where(live_in, v, 0.0)

    u, v = apply_wall_bcs_2d(u, v, gj, gi, bc, gjmax, gimax)
    u = apply_special_bc_2d(u, gj, gi, problem, gjmax, gimax, dy, ylength,
                            u.dtype, prof_dtype)
    if masked:
        fl = fw2[slot]
        u_face, v_face = _obstacle_faces(fl, gj, gi, gjmax, gimax)
        u, v = apply_obstacle_velocity_bc_window(u, v, fl, u_face, v_face)

    f_full, g_full = ops.fg_predictor_terms(
        u, v, dt, re, gx, gy, gamma, dx, dy
    )
    interior = (gj >= 1) & (gj <= gjmax) & (gi >= 1) & (gi <= gimax)
    rows = (gj >= 1) & (gj <= gjmax)
    cols = (gi >= 1) & (gi <= gimax)
    f = jnp.where(interior, f_full, 0.0)
    g = jnp.where(interior, g_full, 0.0)
    # wall fixups (apply_fg_wall_fixups / gated fg_fixups): F carries U on
    # vertical walls, G carries V on horizontal walls
    f = jnp.where((gi == 0) & rows, u, f)
    f = jnp.where((gi == gimax) & rows, u, f)
    g = jnp.where((gj == 0) & cols, v, g)
    g = jnp.where((gj == gjmax) & cols, v, g)
    if masked:
        one = jnp.ones((), u.dtype)
        f = u_face * f + (one - u_face) * u
        g = v_face * g + (one - v_face) * v

    # RHS clipped to the LOCAL interior too: the jnp dist chain leaves the
    # extended block's own ring zero (its solve exchanges rhs halos before
    # reading them) — identical to the global clip on a single device
    local_int = (
        (a_j >= ext_pad + 1) & (a_j <= ext_pad + ljmax)
        & (a_i >= ext_pad + 1) & (a_i <= ext_pad + limax)
    )
    rhs = jnp.where(
        interior & local_int, ops.rhs_terms(f, g, dt, dx, dy), 0.0
    )

    @pl.when(b >= 2)
    def _():
        for c in store(b - 2, slot):
            c.wait()

    for q, arr in enumerate((u, v, f, g, rhs)):
        ob2[slot, q] = arr[h: h + br, :]
    for c in store(b, slot):
        c.start()

    @pl.when(b == nblocks - 1)
    def _():
        for c in store(b, slot):
            c.wait()
        if nblocks > 1:  # static: drain the previous slot's stores too
            for c in store(b - 1, nslot):
                c.wait()


def _post_kernel(
    sref,    # SMEM scalar prefetch: int32[2] = (joff, ioff)
    dt_ref,  # SMEM (1, 1)
    *refs,   # [u, v, f, g, p(, flg)] + [u_out, v_out, umax, vmax] + scratch
    block_rows: int,
    nblocks: int,
    gjmax: int,
    gimax: int,
    ext_pad: int,
    halo: int,
    dx: float,
    dy: float,
    masked: bool,
    ragged: bool,
    dynamic: bool = False,
):
    """adaptUV + the CFL max|u|/max|v| reduction. u/v/f/g ride as owned
    bands (adaptUV reads them at the center only); p (and the flag, whose
    v_face needs one north row) ride as halo windows. The maxes scan every
    cell of the global extended array exactly once across blocks — the
    maxElement ghost-inclusive quirk — masked to the valid region so dist
    callers' stale deep-halo rows never leak in. `dynamic` as in
    _pre_kernel: extents/cell sizes as SMEM scalars (shape-class mode)."""
    if dynamic:
        ext_ref, geo_ref, *refs = refs
    if masked:
        (ub, vb, fb, gb, p_in, flg, u_out, v_out, umax, vmax,
         bw2, pw2, fw2, ob2, macc, ld_sem, st_sem) = refs
    else:
        (ub, vb, fb, gb, p_in, u_out, v_out, umax, vmax,
         bw2, pw2, ob2, macc, ld_sem, st_sem) = refs
        flg = fw2 = None
    b = pl.program_id(0)
    br = block_rows
    h = halo
    slot = b % 2
    nslot = (b + 1) % 2
    joff = sref[0]
    ioff = sref[1]
    dt = dt_ref[0, 0]
    if dynamic:
        gjmax = ext_ref[0, 0]
        gimax = ext_ref[0, 1]
        dx = geo_ref[0, 0]
        dy = geo_ref[0, 1]

    def load(k, s):
        copies = [
            pltpu.make_async_copy(
                arr.at[pl.ds(h + k * br, br), :], bw2.at[s, q],
                ld_sem.at[s, q])
            for q, arr in enumerate((ub, vb, fb, gb))
        ]
        copies.append(pltpu.make_async_copy(
            p_in.at[pl.ds(k * br, br + 2 * h), :], pw2.at[s],
            ld_sem.at[s, 4]))
        if masked:
            copies.append(pltpu.make_async_copy(
                flg.at[pl.ds(k * br, br + 2 * h), :], fw2.at[s],
                ld_sem.at[s, 5]))
        return copies

    def store(k, s):
        return [
            pltpu.make_async_copy(
                ob2.at[s, q], arr.at[pl.ds(h + k * br, br)],
                st_sem.at[s, q])
            for q, arr in enumerate((u_out, v_out))
        ]

    @pl.when(b == 0)
    def _():
        macc[...] = jnp.zeros_like(macc)
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    u = bw2[slot, 0]
    v = bw2[slot, 1]
    f = bw2[slot, 2]
    g = bw2[slot, 3]
    pw = pw2[slot]
    pc = pw[h: h + br, :]

    def roll_p(x, shift, axis):
        # adapt_terms' neighbour contract on the p window: the north
        # neighbour comes from the halo row above the owned band, the east
        # one is an in-row roll (identical values at every unmasked cell).
        # The axis-0 slice hard-codes roll(p, -1, axis=0); trace-time
        # assert rather than silently serving the p halo for anything else
        if axis == 0:
            assert x is pc and shift == -1, (
                "fused POST kernel only supports adapt_terms' "
                "roll(p, -1, axis=0); got shift="
                f"{shift} on axis 0"
            )
            return pw[h + 1: h + br + 1, :]
        return jnp.roll(x, shift, axis=axis)

    rho = b * br + jax.lax.broadcasted_iota(jnp.int32, u.shape, 0)
    a_j = rho
    a_i = jax.lax.broadcasted_iota(jnp.int32, u.shape, 1)
    gj = a_j - ext_pad + joff
    gi = a_i - ext_pad + ioff
    interior = (gj >= 1) & (gj <= gjmax) & (gi >= 1) & (gi <= gimax)

    ua, va = ops.adapt_terms(f, g, pc, dt, dx, dy, roll=roll_p)
    if masked:
        fl = fw2[slot]
        u_face, v_face = _obstacle_faces(
            fl[h: h + br, :], gj, gi, gjmax, gimax,
            roll=lambda x, s, axis: (
                fl[h + 1: h + br + 1, :] if axis == 0
                else jnp.roll(x, s, axis=axis)
            ),
        )
        ua = ua * u_face
        va = va * v_face
    u = jnp.where(interior, ua, u)
    v = jnp.where(interior, va, v)
    if ragged:
        # the jnp ragged chain's live-mask MULTIPLY (ragged2d.live_masks),
        # op-for-op: dead pad cells go to zero after the projection so the
        # next step's ghost-inclusive CFL scan never sees garbage
        live = ((gj <= gjmax + 1) & (gi <= gimax + 1)).astype(u.dtype)
        u = u * live
        v = v * live

    @pl.when(b >= 2)
    def _():
        for c in store(b - 2, slot):
            c.wait()

    ob2[slot, 0] = u
    ob2[slot, 1] = v
    for c in store(b, slot):
        c.start()

    # ghost-inclusive maxElement (solver.c:193-202 quirk): every global
    # extended cell, dead padding and stale deep halos excluded
    valid = (gj >= 0) & (gj <= gjmax + 1) & (gi >= 0) & (gi <= gimax + 1)
    zero = jnp.zeros((), u.dtype)
    au = jnp.max(jnp.where(valid, jnp.abs(u), zero), axis=0, keepdims=True)
    av = jnp.max(jnp.where(valid, jnp.abs(v), zero), axis=0, keepdims=True)
    macc[0:1, :] = jnp.maximum(macc[0:1, :], au)
    macc[1:2, :] = jnp.maximum(macc[1:2, :], av)

    @pl.when(b == nblocks - 1)
    def _():
        umax[0, 0] = jnp.max(macc[0:1, :])
        vmax[0, 0] = jnp.max(macc[1:2, :])
        for c in store(b, slot):
            c.wait()
        if nblocks > 1:
            for c in store(b - 1, nslot):
                c.wait()


def fused_vmem_bytes(br: int, h: int, wp: int, itemsize: int,
                     masked: bool) -> int:
    """Scratch bytes of the LARGER of the two kernels (pre: 2-3 windows +
    5 out bands; post: 4 in bands + 1-2 windows + 2 out bands), double
    buffered."""
    win = (br + 2 * h) * wp
    band = br * wp
    pre = 2 * ((3 if masked else 2) * win + 5 * band)
    post = 2 * (4 * band + (2 if masked else 1) * win + 2 * band)
    return itemsize * max(pre, post)


def fused_feasible(br: int, h: int, wp: int, itemsize: int,
                   masked: bool) -> bool:
    return fused_vmem_bytes(br, h, wp, itemsize, masked) <= VMEM_LIMIT_BYTES // 2


def _layout(ext_rows: int, ext_cols: int, dtype, block_rows):
    h = fuse_halo(dtype)
    if block_rows is None:
        block_rows = pick_block_rows_tblock(ext_rows - 2, ext_cols - 2,
                                            dtype, 1)
    wp = padded_width(ext_cols - 2)
    nblocks = -(-ext_rows // block_rows)
    rp = nblocks * block_rows + 2 * h
    return h, block_rows, wp, nblocks, rp


def _geom(param, gjmax, gimax, dtype, jl, il, ext_pad, fluid, prof_dtype,
          block_rows, interpret):
    """Shared geometry/feasibility resolution for the pre/post builders.
    `fluid` is None (no obstacles), a global (jmax+2, imax+2) 0/1 array
    (single-device: baked in as a padded constant), or True (distributed:
    the per-shard flag block is an extra call-time argument)."""
    if pltpu is None:
        raise ValueError("pallas TPU backend unavailable")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)
    ljmax = gjmax if jl is None else jl
    limax = gimax if il is None else il
    ext_rows = ljmax + 2 + 2 * ext_pad
    ext_cols = limax + 2 + 2 * ext_pad
    h, block_rows, wp, nblocks, rp = _layout(ext_rows, ext_cols, dtype,
                                             block_rows)
    itemsize = jnp.dtype(dtype).itemsize
    masked = fluid is not None
    if not fused_feasible(block_rows, h, wp, itemsize, masked):
        raise ValueError(
            f"fused step-phase scratch {fused_vmem_bytes(block_rows, h, wp, itemsize, masked) >> 20} MiB "
            f"exceeds the VMEM budget (block_rows={block_rows}, h={h}, "
            f"wp={wp}); the jnp phase chain is the fallback"
        )
    if prof_dtype is None:
        prof_dtype = dtype

    def _pad(x):
        return pad_array(x, block_rows, h)

    def _unpad(xp):
        return unpad_array(xp, ext_rows - 2, ext_cols - 2, h)

    flg_padded = None
    if masked and fluid is not True:
        import numpy as np

        flg_padded = _pad(jnp.asarray(np.asarray(fluid), dtype))
    return (interpret, ljmax, limax, h, block_rows, wp, nblocks, rp,
            masked, prof_dtype, _pad, _unpad, flg_padded)


def fused_layout_2d(jmax: int, imax: int, dtype, block_rows=None):
    """(block_rows, halo) of the single-device fused padded layout — what
    make_fused_step_2d resolves to. Callers that want the pressure solve on
    the SAME layout (the p-layout fold, models/ns2d) read it here and pass
    block_rows to both builders."""
    h, br, _wp, _nb, _rp = _layout(jmax + 2, imax + 2, dtype, block_rows)
    return br, h


def fused_deep_layout_2d(jl: int, il: int, dtype, ext_pad: int,
                         block_rows=None):
    """(block_rows, halo, width, nblocks) of the distributed deep-halo
    padded layout — the geometry `parallel/overlap.region_plan` bands
    over when the PRE halves are grid-restricted
    (`tpu_overlap_restrict`)."""
    h, br, wp, nb, _rp = _layout(jl + 2 + 2 * ext_pad,
                                 il + 2 + 2 * ext_pad, dtype, block_rows)
    return br, h, wp, nb


def make_fused_pre_2d(
    param,
    gjmax: int,
    gimax: int,
    dx: float,
    dy: float,
    dtype,
    *,
    jl: int | None = None,
    il: int | None = None,
    ext_pad: int = 0,
    fluid=None,
    prof_dtype=None,
    block_rows: int | None = None,
    interpret: bool | None = None,
    grid_bands: tuple | None = None,
    dynamic: bool = False,
):
    """Build the PRE kernel for one grid/shard geometry:
      pre(offs_i32[2], dt_11, u_pad, v_pad) -> (u', v', f, g, rhs)  [padded]
    plus (pad, unpad, halo) for its layout. Single-device: jl/il omitted,
    ext_pad 0, offsets zeros. Distributed: jl/il are the shard's interior
    extents, ext_pad = FUSE_DEEP_HALO - 1, arrays are the padded deep-halo
    blocks. fluid=True (distributed obstacles) appends a call-time flag
    argument: pre(offs, dt11, u_pad, v_pad, flg_pad), flg_pad the padded
    per-shard deep-halo slice of the global flag. Raises ValueError on
    VMEM infeasibility — the caller's contract is to fall back to the jnp
    chain.

    `grid_bands` (parallel/overlap.region_plan) restricts the Pallas grid
    to ((start_row, n_blocks), ...) row bands of the SAME padded layout —
    the grid-restricted overlap halves. Outputs outside the bands are
    never stored (the interior-merge mask must not select them); the
    layout, call signature and every stored value inside the bands are
    identical to the full sweep's (the kernel stays globally gated).

    `dynamic=True` (the shape-class chunk, fleet/shapeclass.py): gjmax/
    gimax set only the padded CLASS geometry — the live extents and the
    per-lane cell sizes become call-time SMEM scalars, so the call grows
    two operands: pre(offs, ext_i32_12, geo_12, dt11, u_pad, v_pad) with
    ext = (jmax, imax) and geo = (dx, dy). Single-device only
    (incompatible with fluid/grid_bands — class-ineligible modes)."""
    if dynamic and (fluid is not None or grid_bands is not None):
        raise ValueError(
            "dynamic extents are the single-device shape-class mode "
            "(no obstacle flags, no grid bands)")
    (interpret, ljmax, limax, h, block_rows, wp, nblocks, rp, masked,
     prof_dtype, _pad, _unpad, flg_padded) = _geom(
        param, gjmax, gimax, dtype, jl, il, ext_pad, fluid, prof_dtype,
        block_rows, interpret)
    bc = (param.bcLeft, param.bcRight, param.bcBottom, param.bcTop)
    if grid_bands is not None:
        from ..parallel.overlap import check_bands

        check_bands(grid_bands, block_rows, nblocks)
        nblocks = sum(n for _, n in grid_bands)

    pre_kernel = functools.partial(
        _pre_kernel,
        bands=grid_bands,
        block_rows=block_rows,
        nblocks=nblocks,
        gjmax=gjmax,
        gimax=gimax,
        ljmax=ljmax,
        limax=limax,
        ext_pad=ext_pad,
        halo=h,
        bc=bc,
        problem=param.name,
        re=param.re,
        gx=param.gx,
        gy=param.gy,
        gamma=param.gamma,
        dx=dx,
        dy=dy,
        ylength=param.ylength,
        prof_dtype=prof_dtype,
        masked=masked,
        dynamic=dynamic,
    )
    n_in = 3 if masked else 2
    pre_scratch = [
        pltpu.VMEM((2, block_rows + 2 * h, wp), dtype),
        pltpu.VMEM((2, block_rows + 2 * h, wp), dtype),
    ]
    if masked:
        pre_scratch.append(pltpu.VMEM((2, block_rows + 2 * h, wp), dtype))
    pre_scratch += [
        pltpu.VMEM((2, 5, block_rows, wp), dtype),
        pltpu.SemaphoreType.DMA((2, n_in)),
        pltpu.SemaphoreType.DMA((2, 5)),
    ]
    pre_call = pl.pallas_call(
        pre_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nblocks,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
            * (3 if dynamic else 1)
            + [pl.BlockSpec(memory_space=pl.ANY)] * n_in,
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 5,
            scratch_shapes=pre_scratch,
        ),
        out_shape=[jax.ShapeDtypeStruct((rp, wp), dtype)] * 5,
        compiler_params=CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )

    if dynamic:

        def pre(offs, ext, geo, dt11, u_pad, v_pad):
            return pre_call(offs, dt11, ext, geo, u_pad, v_pad)
    elif masked and flg_padded is None:

        def pre(offs, dt11, u_pad, v_pad, flg_pad):
            return pre_call(offs, dt11, u_pad, v_pad, flg_pad)
    elif masked:

        def pre(offs, dt11, u_pad, v_pad):
            return pre_call(offs, dt11, u_pad, v_pad, flg_padded)
    else:

        def pre(offs, dt11, u_pad, v_pad):
            return pre_call(offs, dt11, u_pad, v_pad)

    return pre, _pad, _unpad, h


def make_fused_post_2d(
    param,
    gjmax: int,
    gimax: int,
    dx: float,
    dy: float,
    dtype,
    *,
    jl: int | None = None,
    il: int | None = None,
    ext_pad: int = 0,
    fluid=None,
    ragged: bool = False,
    block_rows: int | None = None,
    interpret: bool | None = None,
    dynamic: bool = False,
):
    """Build the POST kernel (same geometry contract as make_fused_pre_2d):
      post(offs_i32[2], dt_11, u_pad, v_pad, f_pad, g_pad, p_pad)
          -> (u'', v'', umax, vmax)                     [padded + scalars]
    Distributed callers build it on the PLAIN extended block (ext_pad 0):
    adaptUV reads only center/+1 values, all inside the exchanged halo-1
    ring. fluid=True appends a call-time flag argument (the padded
    per-shard EXTENDED-block slice of the global flag); ragged=True
    appends the dead-cell live-mask multiply after the projection.
    `dynamic=True` as in make_fused_pre_2d: the call becomes
    post(offs, ext, geo, dt11, u, v, f, g, p) with extent-gated masks."""
    if dynamic and fluid is not None:
        raise ValueError(
            "dynamic extents are the single-device shape-class mode "
            "(no obstacle flags)")
    (interpret, ljmax, limax, h, block_rows, wp, nblocks, rp, masked,
     _prof_dtype, _pad, _unpad, flg_padded) = _geom(
        param, gjmax, gimax, dtype, jl, il, ext_pad, fluid, None,
        block_rows, interpret)
    del ljmax, limax

    post_kernel = functools.partial(
        _post_kernel,
        block_rows=block_rows,
        nblocks=nblocks,
        gjmax=gjmax,
        gimax=gimax,
        ext_pad=ext_pad,
        halo=h,
        dx=dx,
        dy=dy,
        masked=masked,
        ragged=ragged,
        dynamic=dynamic,
    )
    n_in_post = 6 if masked else 5
    post_scratch = [
        pltpu.VMEM((2, 4, block_rows, wp), dtype),
        pltpu.VMEM((2, block_rows + 2 * h, wp), dtype),
    ]
    if masked:
        post_scratch.append(pltpu.VMEM((2, block_rows + 2 * h, wp), dtype))
    post_scratch += [
        pltpu.VMEM((2, 2, block_rows, wp), dtype),
        pltpu.VMEM((2, wp), dtype),  # per-lane |u|/|v| max accumulators
        pltpu.SemaphoreType.DMA((2, n_in_post)),
        pltpu.SemaphoreType.DMA((2, 2)),
    ]
    post_call = pl.pallas_call(
        post_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nblocks,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
            * (3 if dynamic else 1)
            + [pl.BlockSpec(memory_space=pl.ANY)] * n_in_post,
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2
            + [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2,
            scratch_shapes=post_scratch,
        ),
        out_shape=[jax.ShapeDtypeStruct((rp, wp), dtype)] * 2
        + [jax.ShapeDtypeStruct((1, 1), dtype)] * 2,
        compiler_params=CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )

    if dynamic:

        def post(offs, ext, geo, dt11, u_pad, v_pad, f_pad, g_pad, p_pad):
            u_pad, v_pad, um, vm = post_call(
                offs, dt11, ext, geo, u_pad, v_pad, f_pad, g_pad, p_pad
            )
            return u_pad, v_pad, um[0, 0], vm[0, 0]
    elif masked and flg_padded is None:

        def post(offs, dt11, u_pad, v_pad, f_pad, g_pad, p_pad, flg_pad):
            u_pad, v_pad, um, vm = post_call(
                offs, dt11, u_pad, v_pad, f_pad, g_pad, p_pad, flg_pad
            )
            return u_pad, v_pad, um[0, 0], vm[0, 0]
    elif masked:

        def post(offs, dt11, u_pad, v_pad, f_pad, g_pad, p_pad):
            u_pad, v_pad, um, vm = post_call(
                offs, dt11, u_pad, v_pad, f_pad, g_pad, p_pad, flg_padded
            )
            return u_pad, v_pad, um[0, 0], vm[0, 0]
    else:

        def post(offs, dt11, u_pad, v_pad, f_pad, g_pad, p_pad):
            u_pad, v_pad, um, vm = post_call(
                offs, dt11, u_pad, v_pad, f_pad, g_pad, p_pad
            )
            return u_pad, v_pad, um[0, 0], vm[0, 0]

    return post, _pad, _unpad, h


def make_fused_step_2d(
    param,
    gjmax: int,
    gimax: int,
    dx: float,
    dy: float,
    dtype,
    *,
    fluid=None,
    prof_dtype=None,
    block_rows: int | None = None,
    interpret: bool | None = None,
):
    """The single-device composition: PRE and POST on the same whole-grid
    geometry. Returns (pre, post, pad, unpad, halo); see the per-kernel
    builders for the call signatures. `fluid` switches on the obstacle
    mode with the global flag field baked in as a padded constant."""
    pre, _pad, _unpad, h = make_fused_pre_2d(
        param, gjmax, gimax, dx, dy, dtype, fluid=fluid,
        prof_dtype=prof_dtype, block_rows=block_rows, interpret=interpret,
    )
    post, _pad2, _unpad2, _h2 = make_fused_post_2d(
        param, gjmax, gimax, dx, dy, dtype, fluid=fluid,
        block_rows=block_rows, interpret=interpret,
    )
    return pre, post, _pad, _unpad, h


_PROBE_OK: bool | None = None


def probe_fused_2d() -> bool:
    """One-time smoke test of the fused step-phase pair on a tiny grid on
    the real backend (the sor_pallas.probe_pallas contract): toolchain-wide
    failures surface once and the dispatcher keeps the jnp chain."""
    global _PROBE_OK
    if _PROBE_OK is None:
        try:
            from ..utils.params import Parameter

            param = Parameter(name="dcavity", imax=126, jmax=126)
            pre, post, _pad, _unpad, _h = make_fused_step_2d(
                param, 126, 126, 1.0 / 126, 1.0 / 126, jnp.float32,
                interpret=False,
            )
            z = _pad(jnp.zeros((128, 128), jnp.float32))
            offs = jnp.zeros((2,), jnp.int32)
            dt11 = jnp.full((1, 1), 0.01, jnp.float32)
            up, vp, fp, gp, _r = pre(offs, dt11, z, z)
            up, vp, um, _vm = post(offs, dt11, up, vp, fp, gp, z)
            float(um)  # force completion: async errors surface here
            _PROBE_OK = True
        except Exception:  # lint: allow(broad-except) — probe contract: any failure means "don't dispatch"
            import warnings

            warnings.warn(
                "fused NS step-phase kernels unavailable; keeping the jnp "
                "phase chain",
                stacklevel=2,
            )
            _PROBE_OK = False
    return _PROBE_OK
