"""Per-shard Pallas kernel for the DISTRIBUTED octant-layout 3-D SOR.

The 3-D companion of ops/sor_qdist.py (≙ the reference's per-rank 3-D SOR,
assignment-6/src/solver.c:175-297, running on every chip of the mesh): the
temporal-blocked octant kernel of sor3d_pallas.make_rb_iter_tblock_3d_octants
generalized to a shard of a ("k","j","i") mesh — masks from GLOBAL octant
coordinates via three scalar-prefetch offsets, updates clipped to the stored
logical region with a frozen outermost ring, owned-only residual. Layout and
jnp twin: parallel/octants_dist.py (keep the mask formulas in lockstep)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..parallel.octants_dist import OGeom, QIDX
from .sor_octants import BITS, EVEN, ODD, _flip
from .sor_pallas import CompilerParams, VMEM_LIMIT_BYTES, _check_dtype, pltpu


def octants_dist_vmem_bytes(g: OGeom, itemsize: int) -> int:
    win = 16 * (g.bk + 2 * g.h) * g.jp2 * g.ip2
    out = 16 * g.bk * g.jp2 * g.ip2
    return itemsize * (2 * win + out + g.ip2)


def octants_dist_feasible(g: OGeom, itemsize: int) -> bool:
    return octants_dist_vmem_bytes(g, itemsize) <= VMEM_LIMIT_BYTES // 2


def _odist_kernel(
    sref,   # SMEM scalar prefetch: int32[3] = (qoff_k, qoff_j, qoff_i)
    p_in,   # ANY (8, sp, jp2, ip2) stacked stored volume, BITS order
    rhs,    # ANY (8, sp, jp2, ip2)
    p_out,  # ANY (8, sp, jp2, ip2)
    res,    # SMEM (1, 1)
    pw2,    # VMEM (16, bk+2h, jp2, ip2): slot*8 + octant (Mosaic wants <=4-D)
    rw2,    # VMEM (16, bk+2h, jp2, ip2)
    ob2,    # VMEM (16, bk, jp2, ip2)
    vacc,   # VMEM (1, ip2)
    ld_sem,  # DMA (2, 16)
    st_sem,  # DMA (2, 8)
    *,
    g: OGeom,
    factor: float,
    idx2: float,
    idy2: float,
    idz2: float,
):
    b = pl.program_id(0)
    bk = g.bk
    h = g.h
    slot = b % 2
    nslot = (b + 1) % 2
    qidx = QIDX
    # axes without a deep halo (mesh size 1) have a statically-zero shard
    # offset: substituting the constant lets Mosaic fold their masks to
    # static iota compares, as in the single-device octant kernel
    qoff = tuple(sref[a] if g.d[a] > 0 else 0 for a in range(3))

    def load(k, s):
        copies = []
        for qi in range(8):
            copies.append(pltpu.make_async_copy(
                p_in.at[qi, pl.ds(k * bk, bk + 2 * h)], pw2.at[s * 8 + qi],
                ld_sem.at[s, qi]))
            copies.append(pltpu.make_async_copy(
                rhs.at[qi, pl.ds(k * bk, bk + 2 * h)], rw2.at[s * 8 + qi],
                ld_sem.at[s, 8 + qi]))
        return copies

    def store(k, s):
        return [pltpu.make_async_copy(
            ob2.at[s * 8 + qi], p_out.at[qi, pl.ds(h + k * bk, bk)],
            st_sem.at[s, qi]) for qi in range(8)]

    @pl.when(b == 0)
    def _():
        res[0, 0] = jnp.zeros((), p_out.dtype)
        vacc[...] = jnp.zeros_like(vacc)
        for c in load(0, 0):
            c.start()

    @pl.when(b + 1 < g.nblocks)
    def _():
        for c in load(b + 1, nslot):
            c.start()

    for c in load(b, slot):
        c.wait()

    octs = {bits: pw2[slot * 8 + qidx[bits]] for bits in BITS}
    rhs_o = {bits: rw2[slot * 8 + qidx[bits]] for bits in BITS}

    shape = octs[(0, 0, 0)].shape
    # stored coords of window cell: s = b*bk + wk, r = wj, c = wi
    st_s = b * bk + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    st_r = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    st_c = jax.lax.broadcasted_iota(jnp.int32, shape, 2)
    stored = (st_s, st_r, st_c)
    lam = (st_s - h, st_r, st_c)
    go = tuple(lam[a] - g.d[a] + qoff[a] for a in range(3))
    # frozen-ring clip only on deep-halo axes (octants_dist.o_masks)
    spans = (g.kq, g.jq, g.iq)
    valid_upd = None
    for a in range(3):
        if g.d[a] == 0:
            continue
        term = (lam[a] >= 1) & (lam[a] <= spans[a] - 2)
        valid_upd = term if valid_upd is None else (valid_upd & term)
    if valid_upd is None:
        valid_upd = jnp.ones_like(lam[0], dtype=bool)
    valid_any = (
        (lam[0] >= 0) & (lam[0] < g.kq)
        & (lam[1] >= 0) & (lam[1] < g.jq)
        & (lam[2] >= 0) & (lam[2] < g.iq)
    )

    def ax_int(axis, bit):
        if bit == 0:
            return (go[axis] >= 1) & (go[axis] <= g.gmax2(axis))
        return (go[axis] >= 0) & (go[axis] <= g.gmax2(axis) - 1)

    def ax_own(axis, bit):
        from ..parallel.octants_dist import _owned_start

        os = _owned_start(g, axis, bit)
        return (stored[axis] >= os) & (stored[axis] < os + g.local2(axis))

    # ownership differs from the update interior only on deep-halo axes
    # (redundantly-recomputed ghost cells); on d_ax = 0 axes rm is already
    # zero outside owned cells, so those ax_own terms (and, on an all-owned
    # shard, the whole residual select) drop out
    own_axes = [a for a in range(3) if g.d[a] > 0]
    m_upd = {}
    m_own = {}
    for bits in BITS:
        m_upd[bits] = (
            ax_int(0, bits[0]) & ax_int(1, bits[1]) & ax_int(2, bits[2])
            & valid_upd
        )
        own = None
        for a in own_axes:
            term = ax_own(a, bits[a])
            own = term if own is None else (own & term)
        m_own[bits] = own

    def nbrs(bits):
        def ax_pair(axis):
            partner = octs[_flip(bits, axis)]
            if bits[axis] == 0:
                return jnp.roll(partner, 1, axis), partner
            return partner, jnp.roll(partner, -1, axis)

        f, bk_ = ax_pair(0)
        s_, n_ = ax_pair(1)
        w, e = ax_pair(2)
        return w, e, s_, n_, f, bk_

    resids = {}
    for _t in range(g.n):
        for group in (ODD, EVEN):
            for bits in group:
                cen = octs[bits]
                w, e, s_, n_, f, bk_ = nbrs(bits)
                r = rhs_o[bits] - (
                    (e - 2.0 * cen + w) * idx2
                    + (n_ - 2.0 * cen + s_) * idy2
                    + (bk_ - 2.0 * cen + f) * idz2
                )
                rm = jnp.where(m_upd[bits], r, jnp.zeros_like(r))
                octs[bits] = cen - factor * rm
                resids[bits] = rm
        # globally-gated Neumann face refresh: same-index partner selects
        for axis in range(3):
            for hi in (False, True):
                plane = go[axis] == (g.gmax2(axis) if hi else 0)
                for bits in BITS:
                    if bits[axis] != (1 if hi else 0):
                        continue
                    a2, a3 = [a for a in range(3) if a != axis]
                    sel = (plane & ax_int(a2, bits[a2])
                           & ax_int(a3, bits[a3]) & valid_any)
                    octs[bits] = jnp.where(
                        sel, octs[_flip(bits, axis)], octs[bits]
                    )

    @pl.when(b >= 2)
    def _():
        for c in store(b - 2, slot):
            c.wait()

    for bits in BITS:
        ob2[slot * 8 + qidx[bits]] = octs[bits][h: h + bk]
    for c in store(b, slot):
        c.start()

    acc = jnp.zeros_like(vacc[...])
    for bits in BITS:
        rq = resids[bits]
        if m_own[bits] is None:
            rq_own = rq * rq
        else:
            rq_own = jnp.where(m_own[bits], rq * rq, jnp.zeros_like(rq))
        acc = acc + jnp.sum(rq_own[h: h + bk], axis=(0, 1))[None, :]
    vacc[...] += acc

    @pl.when(b == g.nblocks - 1)
    def _():
        res[0, 0] += jnp.sum(vacc[...])
        for c in store(b, slot):
            c.wait()
        if g.nblocks > 1:
            for c in store(b - 1, nslot):
                c.wait()


def make_rb_iters_odist(g: OGeom, dx: float, dy: float, dz: float,
                        omega: float, dtype, *,
                        interpret: bool | None = None):
    """Build `(qoffs_i32[3], p_stacked, rhs_stacked) ->
    (p_stacked', owned res sum of last iter)` performing g.n 3-D red-black
    iterations on the (8, sp, jp2, ip2) stored volume. Call INSIDE shard_map
    with qoffs = [koff//2, joff//2, ioff//2]."""
    if pltpu is None:
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_dtype(dtype, interpret)
    itemsize = jnp.dtype(dtype).itemsize
    if not octants_dist_feasible(g, itemsize):
        raise ValueError(
            f"octants-dist scratch {octants_dist_vmem_bytes(g, itemsize) >> 20}"
            f" MiB exceeds the VMEM budget (bk={g.bk}, h={g.h}, "
            f"plane={g.jp2}x{g.ip2}); reduce tpu_ca_inner or the shard size"
        )

    from ..models.ns3d import sor_coefficients_3d

    factor, idx2, idy2, idz2 = sor_coefficients_3d(dx, dy, dz, omega)
    kernel = functools.partial(
        _odist_kernel, g=g, factor=factor, idx2=idx2, idy2=idy2, idz2=idz2
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g.nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((16, g.bk + 2 * g.h, g.jp2, g.ip2), dtype),
            pltpu.VMEM((16, g.bk + 2 * g.h, g.jp2, g.ip2), dtype),
            pltpu.VMEM((16, g.bk, g.jp2, g.ip2), dtype),
            pltpu.VMEM((1, g.ip2), dtype),
            pltpu.SemaphoreType.DMA((2, 16)),
            pltpu.SemaphoreType.DMA((2, 8)),
        ],
    )
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((8, g.sp, g.jp2, g.ip2), dtype),
            jax.ShapeDtypeStruct((1, 1), dtype),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )

    def rb_iters(qoffs, p_stacked, rhs_stacked):
        p_stacked, res = call(qoffs, p_stacked, rhs_stacked)
        return p_stacked, res[0, 0]

    return rb_iters
