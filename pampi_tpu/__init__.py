"""pampi_tpu — a TPU-native (JAX/XLA/Pallas/shard_map) stencil & linear-algebra
framework with the capabilities of `alirostami1/practical-parallel-algorithms-with-mpi`.

Built from scratch, TPU-first: fields are JAX arrays sharded over a 1/2/3-D
`jax.sharding.Mesh`; the reference's MPI halo exchange / ring shifts / Allreduce
(see /root/reference, e.g. assignment-6/src/comm.h:104-138) become `lax.ppermute`
and `lax.psum`/`lax.pmax` inside `shard_map`-wrapped, jitted step functions.

Layout (mirrors the layer map in SURVEY.md §1):
  utils/     L1/L2/L3 — .par config, grid descriptor, timing, progress, .dat/VTK I/O
  parallel/  L4       — the ten-function Comm API, TPU-native (mesh + ppermute + psum)
  ops/       L5 math  — stencil sweeps, momentum predictor, BC masks, Pallas kernels
  models/    L5/L6    — Poisson, NS-2D, NS-3D solvers and DMVM drivers
"""

__version__ = "0.1.0"
