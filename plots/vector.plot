# gnuplot: velocity quiver from velocity.dat rows `x y u v |vel|`
# (viz parity with the reference's vector.plot; color by magnitude)
set terminal png size 1200,600 enhanced font ,12
set output 'velocity.png'
set palette defined (0 "blue", 1 "red")
set cbrange [*:*]
plot 'velocity.dat' using 1:2:3:4:5 with vectors head size 0.01,20,60 \
     filled lc palette notitle
