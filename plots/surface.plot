# gnuplot: pressure surface from pressure.dat / p.dat triples
# (viz parity with the reference's surface.plot committed next to the 2-D
# solvers; drive with `gnuplot plots/surface.plot` after a run)
set terminal png size 1024,768 enhanced font ,12
set output 'p.png'
set grid
set hidden3d
set dgrid3d 50,50 qnorm 2
splot 'pressure.dat' using 1:2:3 with lines notitle
