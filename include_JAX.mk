# TPU backend via the JAX process (≙ include_<TAG>.mk toolchain files,
# e.g. /root/reference/assignment-6/include_CLANG.mk — here the "toolchain"
# is the C host compiler for the native layer plus the Python interpreter
# that owns the XLA/Pallas compute path).
CC = gcc
CFLAGS = -O3 -std=c99 -D_POSIX_C_SOURCE=200809L -Wall -Wextra -fPIC
PAMPI_PYTHON ?= python
DEFINES = -DPAMPI_PYTHON_DEFAULT=\"$(PAMPI_PYTHON)\"
