# Plain-GCC native build (lib + shim; the shim still delegates to whatever
# python is on PATH at run time).
CC = gcc
CFLAGS = -O3 -std=c99 -D_POSIX_C_SOURCE=200809L -Wall -Wextra -fPIC
DEFINES =
